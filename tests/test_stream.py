"""Streaming executor tests, modeled on the reference's executor tests
(chunk DSL in, snapshot of emitted changelog out — SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.expr.node import col
from risingwave_tpu.expr.agg import AggCall, count_star
from risingwave_tpu.stream.executor import FilterExecutor, ProjectExecutor
from risingwave_tpu.stream.fragment import Fragment
from risingwave_tpu.stream.hash_agg import HashAggExecutor
from risingwave_tpu.stream.materialize import (
    AppendOnlyMaterialize,
    MaterializeExecutor,
)


def _rows(chunk):
    return sorted(chunk.to_rows())


def test_project_filter_fragment():
    schema = Schema.of(("a", DataType.INT64), ("b", DataType.INT64))
    proj = ProjectExecutor(schema, [("a", col("a")), ("c", col("b") * 2)])
    filt = FilterExecutor(proj.out_schema, col("c") > 10)
    frag = Fragment([proj, filt])
    states = frag.init_states()
    chunk = Chunk.from_pretty(
        """
        I I
        +  1 2
        +  2 6
        -  3 10
        """,
        names=["a", "b"],
    )
    states, out = frag.step(states, chunk)
    assert _rows(out) == [(0, 2, 12), (1, 3, 20)]


def test_filter_update_pair_degradation():
    # U- stays, U+ filtered out => U- becomes plain delete (ref filter.rs)
    schema = Schema.of(("a", DataType.INT64))
    filt = FilterExecutor(schema, col("a") < 10)
    frag = Fragment([filt])
    chunk = Chunk.from_pretty(
        """
        I
        U- 5
        U+ 15
        """,
        names=["a"],
    )
    _, out = frag.step(frag.init_states(), chunk)
    assert out.to_rows() == [(1, 5)]  # OP_DELETE

    chunk2 = Chunk.from_pretty(
        """
        I
        U- 15
        U+ 5
        """,
        names=["a"],
    )
    _, out2 = frag.step(frag.init_states(), chunk2)
    assert out2.to_rows() == [(0, 5)]  # OP_INSERT


def _agg_fragment(table_size=64, emit_capacity=8):
    schema = Schema.of(("g", DataType.INT64), ("v", DataType.INT64))
    agg = HashAggExecutor(
        schema,
        group_by=[("g", col("g"))],
        aggs=[count_star(), AggCall("sum", col("v"), "s")],
        table_size=table_size,
        emit_capacity=emit_capacity,
    )
    return Fragment([agg]), agg


def test_hash_agg_insert_then_update():
    frag, agg = _agg_fragment()
    states = frag.init_states()
    states, _ = frag.step(states, Chunk.from_pretty(
        """
        I I
        + 1 10
        + 1 5
        + 2 7
        """,
    names=["g", "v"],
    ))
    states, outs = frag.flush(states, 1)
    assert len(outs) == 1
    assert _rows(outs[0]) == [(0, 1, 2, 15), (0, 2, 1, 7)]

    # second epoch: one more row for group 1 -> U-/U+ pair; group 2 silent
    states, _ = frag.step(states, Chunk.from_pretty(
        """
        I I
        + 1 1
        """,
    names=["g", "v"],
    ))
    states, outs = frag.flush(states, 2)
    rows = outs[0].to_rows()
    assert rows == [(2, 1, 2, 15), (3, 1, 3, 16)]  # U- old, U+ new


def test_hash_agg_retraction_to_empty():
    frag, agg = _agg_fragment()
    states = frag.init_states()
    states, _ = frag.step(states, Chunk.from_pretty(
        """
        I I
        + 1 10
        """,
    names=["g", "v"],
    ))
    states, outs = frag.flush(states, 1)
    assert outs[0].to_rows() == [(0, 1, 1, 10)]
    states, _ = frag.step(states, Chunk.from_pretty(
        """
        I I
        - 1 10
        """,
    names=["g", "v"],
    ))
    states, outs = frag.flush(states, 2)
    assert outs[0].to_rows() == [(1, 1, 1, 10)]  # Delete of the old row

    # re-insert => plain Insert again (emitted flag was cleared)
    states, _ = frag.step(states, Chunk.from_pretty(
        """
        I I
        + 1 3
        """,
    names=["g", "v"],
    ))
    states, outs = frag.flush(states, 3)
    assert outs[0].to_rows() == [(0, 1, 1, 3)]


def test_hash_agg_emit_overflow_drains():
    # 12 dirty groups, emit capacity 8 -> runtime drains in 2 flushes
    frag, agg = _agg_fragment(table_size=64, emit_capacity=8)
    states = frag.init_states()
    arrays = [np.arange(12, dtype=np.int64), np.ones(12, np.int64)]
    schema = Schema.of(("g", DataType.INT64), ("v", DataType.INT64))
    states, _ = frag.step(states, Chunk.from_numpy(schema, arrays))
    states, outs = frag.flush(states, 1)
    n1 = sum(len(o.to_rows()) for o in outs)
    assert n1 == 8
    assert int(agg.pending_dirty(states[0])) == 4
    states, outs2 = frag.flush(states, 1)
    assert sum(len(o.to_rows()) for o in outs2) == 4
    assert int(agg.pending_dirty(states[0])) == 0


def test_hash_agg_min_max_append_only():
    schema = Schema.of(("g", DataType.INT64), ("v", DataType.INT64))
    agg = HashAggExecutor(
        schema,
        group_by=[("g", col("g"))],
        aggs=[AggCall("min", col("v"), "lo"), AggCall("max", col("v"), "hi")],
        table_size=64,
        emit_capacity=8,
    )
    frag = Fragment([agg])
    states = frag.init_states()
    states, _ = frag.step(states, Chunk.from_pretty(
        """
        I I
        + 1 5
        + 1 9
        + 1 2
        """,
    names=["g", "v"],
    ))
    states, outs = frag.flush(states, 1)
    assert outs[0].to_rows() == [(0, 1, 2, 9)]


def test_materialize_upsert():
    schema = Schema.of(("k", DataType.INT64), ("v", DataType.INT64))
    mv = MaterializeExecutor(schema, pk_indices=[0], table_size=64)
    frag = Fragment([mv])
    states = frag.init_states()
    states, _ = frag.step(states, Chunk.from_pretty(
        """
        I I
        + 1 10
        + 2 20
        """,
    names=["g", "v"],
    ))
    states, _ = frag.step(states, Chunk.from_pretty(
        """
        I I
        U- 1 10
        U+ 1 11
        -  2 20
        + 3 30
        """,
    names=["g", "v"],
    ))
    rows = sorted(mv.to_host(states[0]))
    assert rows == [(1, 11), (3, 30)]


def test_append_only_materialize_ring():
    schema = Schema.of(("v", DataType.INT64))
    mv = AppendOnlyMaterialize(schema, ring_size=16)
    frag = Fragment([mv])
    states = frag.init_states()
    arrays = [np.arange(5, dtype=np.int64)]
    states, _ = frag.step(states, Chunk.from_numpy(schema, arrays, capacity=8))
    states, _ = frag.step(
        states, Chunk.from_numpy(schema, [np.arange(5, 10, dtype=np.int64)],
                                 capacity=8)
    )
    rows = mv.to_host(states[0])
    assert [r[0] for r in rows] == list(range(10))


def test_agg_into_materialize_chain():
    """agg flush output flows through trailing materialize in one fragment."""
    schema = Schema.of(("g", DataType.INT64), ("v", DataType.INT64))
    agg = HashAggExecutor(
        schema, [("g", col("g"))], [count_star("n")],
        table_size=64, emit_capacity=8,
    )
    mv = MaterializeExecutor(agg.out_schema, pk_indices=[0], table_size=64)
    frag = Fragment([agg, mv])
    states = frag.init_states()
    states, _ = frag.step(states, Chunk.from_pretty(
        """
        I I
        + 1 0
        + 1 0
        + 2 0
        """,
    names=["g", "v"],
    ))
    states, _ = frag.flush(states, 1)
    assert sorted(mv.to_host(states[1])) == [(1, 2), (2, 1)]
    states, _ = frag.step(states, Chunk.from_pretty(
        """
        I I
        - 1 0
        """,
    names=["g", "v"],
    ))
    states, _ = frag.flush(states, 2)
    assert sorted(mv.to_host(states[1])) == [(1, 1), (2, 1)]


def test_changelog_executor():
    from risingwave_tpu.stream.executor import ChangelogExecutor

    schema = Schema.of(("v", DataType.INT64))
    frag = Fragment([ChangelogExecutor(schema)])
    _, out = frag.step(frag.init_states(), Chunk.from_pretty("""
        I
        + 1
        - 2
        U- 3
        U+ 4
    """, names=["v"]))
    # every row becomes an Insert carrying its original op
    assert out.to_rows() == [(0, 1, 0), (0, 2, 1), (0, 3, 2), (0, 4, 3)]


def test_row_id_gen_executor():
    from risingwave_tpu.stream.executor import RowIdGenExecutor

    schema = Schema.of(("v", DataType.INT64))
    gen = RowIdGenExecutor(schema)
    frag = Fragment([gen])
    st = frag.init_states()
    st, out = frag.step(st, Chunk.from_pretty("""
        I
        + 10
        + 11
    """, names=["v"]))
    assert out.to_rows() == [(0, 10, 0), (0, 11, 1)]
    st, out = frag.step(st, Chunk.from_pretty("""
        I
        + 12
    """, names=["v"]))
    assert out.to_rows() == [(0, 12, 2)]  # counter persists


def test_run_chunks_multi_dispatch_equivalence():
    """run_chunks(n) (one fused dispatch) must advance state and source
    cursor exactly like n run_chunk() calls (the q1 host-overhead
    amortization must not change semantics)."""
    import numpy as np

    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig

    def build():
        eng = Engine(PlannerConfig(
            chunk_capacity=128, agg_table_size=512,
            agg_emit_capacity=256, mv_table_size=512, mv_ring_size=2048,
        ))
        eng.execute(
            "CREATE SOURCE bid (auction BIGINT, bidder BIGINT, "
            "price BIGINT, date_time TIMESTAMP) "
            "WITH (connector='nexmark', nexmark.table='bid')"
        )
        eng.execute(
            "CREATE MATERIALIZED VIEW m AS "
            "SELECT auction, count(*) AS n, sum(price) AS s "
            "FROM bid GROUP BY auction"
        )
        return eng

    a = build()
    job_a = a.jobs[0]
    assert job_a._fused is not None  # nexmark is traceable
    for _ in range(8):
        job_a.run_chunk()
    job_a.inject_barrier()
    rows_a = sorted(map(tuple, a.execute("SELECT * FROM m")))
    off_a = job_a.source.offset

    b = build()
    job_b = b.jobs[0]
    got = job_b.run_chunks(8)
    assert got == 8 * 128
    job_b.inject_barrier()
    rows_b = sorted(map(tuple, b.execute("SELECT * FROM m")))
    assert job_b.source.offset == off_a
    assert rows_b == rows_a and len(rows_a) > 0
