"""Multi-shard (8 virtual devices) dataflow tests.

The reference tests multi-node behaviour in one process with madsim
(SURVEY.md §4.4); here the analog is a virtual 8-device CPU mesh with
the full shard_map + all_to_all path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.expr.agg import AggCall, count_star
from risingwave_tpu.expr.node import col
from risingwave_tpu.stream.hash_agg import HashAggExecutor
from risingwave_tpu.stream.sharded import ShardedJob, make_mesh


pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

SCHEMA = Schema.of(("g", DataType.INT64), ("v", DataType.INT64))


def _source(k0, cap):
    """Synthetic keyed stream: g cycles 0..15, v = ordinal."""
    k = k0 + jnp.arange(cap, dtype=jnp.int64)
    g = k % 16
    return Chunk(
        (g, k),
        jnp.zeros((cap,), jnp.int8),
        jnp.ones((cap,), jnp.bool_),
        SCHEMA,
    )


def test_sharded_count_sum_matches_single_shard():
    mesh = make_mesh(8)
    agg = HashAggExecutor(
        SCHEMA,
        group_by=[("g", col("g"))],
        aggs=[count_star("n"), AggCall("sum", col("v"), "s")],
        table_size=256,
        emit_capacity=64,
    )
    job = ShardedJob(
        mesh,
        source_fn=_source,
        chunk_capacity=32,
        local_executors=[],
        exchange_key_fn=lambda c: [c.column(0)],
        keyed_executors=[agg],
    )
    states = job.init_states()
    states, outs = job.run_epochs(states, barriers=2, chunks_per_barrier=2)

    # ground truth: 8 shards * 2 barriers * 2 chunks * 32 rows
    total = 8 * 2 * 2 * 32
    ks = np.arange(total, dtype=np.int64)
    want_n = {int(g): int((ks % 16 == g).sum()) for g in range(16)}
    want_s = {int(g): int(ks[ks % 16 == g].sum()) for g in range(16)}

    # fold the emitted changelog into a dict (ops applied in order)
    got = {}
    for flush_outs in outs:
        for out in flush_outs:  # each is a [8, cap]-stacked chunk pytree
            leaves = jax.tree.map(np.asarray, out)
            for shard in range(8):
                shard_chunk = jax.tree.map(lambda x: x[shard], leaves)
                ops, cols, _ = shard_chunk.to_host()
                for i in range(len(ops)):
                    g, n, s = int(cols[0][i]), int(cols[1][i]), int(cols[2][i])
                    if ops[i] in (0, 3):
                        got[g] = (n, s)
                    elif ops[i] == 1:
                        got.pop(g, None)
    assert {g: v[0] for g, v in got.items()} == want_n
    assert {g: v[1] for g, v in got.items()} == want_s


def test_each_group_lives_on_exactly_one_shard():
    mesh = make_mesh(8)
    agg = HashAggExecutor(
        SCHEMA, [("g", col("g"))], [count_star("n")],
        table_size=256, emit_capacity=64,
    )
    job = ShardedJob(
        mesh, _source, 32, [], lambda c: [c.column(0)], [agg],
    )
    states = job.init_states()
    states, _ = job.run_epochs(states, barriers=1, chunks_per_barrier=4)
    # inspect per-shard group tables: each group key on exactly one shard
    occupied = np.asarray(jax.device_get(states[0].table.occupied))
    keys = np.asarray(jax.device_get(states[0].table.key_cols[0]))
    owner: dict[int, int] = {}
    for shard in range(8):
        for slot in np.nonzero(occupied[shard])[0]:
            g = int(keys[shard, slot])
            assert g not in owner, f"group {g} on shards {owner[g]} and {shard}"
            owner[g] = shard
    assert len(owner) == 16


def test_shuffle_carries_string_columns():
    """Regression: StrCol columns survive the all_to_all exchange."""
    from jax.sharding import PartitionSpec as P
    from risingwave_tpu.parallel.exchange import shuffle_chunk

    from risingwave_tpu.parallel.exchange import shard_map_nocheck

    schema = Schema.of(("g", DataType.INT64), ("s", DataType.VARCHAR))
    mesh = make_mesh(8)
    cap = 16

    def make_local(shard_g):
        import risingwave_tpu.common.chunk as ck
        data, lens = ck.encode_strings(
            [f"str{i % 4}" for i in range(cap)], 64
        )
        return Chunk(
            (jnp.arange(cap, dtype=jnp.int64) % 4,
             ck.StrCol(jnp.asarray(data), jnp.asarray(lens))),
            jnp.zeros((cap,), jnp.int8),
            jnp.ones((cap,), jnp.bool_),
            schema,
        )

    def body(_):
        chunk = make_local(0)
        out = shuffle_chunk(chunk, [chunk.column(0)], "shard", 8)
        return jax.tree.map(lambda x: x[None], out)

    f = jax.jit(shard_map_nocheck(
        body, mesh=mesh, in_specs=(P("shard"),), out_specs=P("shard"),
    ))
    out = f(jnp.zeros((8,), jnp.int32))
    leaves = jax.tree.map(np.asarray, out)
    total = 0
    for shard in range(8):
        c = jax.tree.map(lambda x: x[shard], leaves)
        ops, cols, _ = c.to_host()
        for i in range(len(ops)):
            g, s = int(cols[0][i]), cols[1][i]
            assert s == f"str{g}"  # string stayed with its key
            total += 1
    assert total == 8 * cap  # nothing lost in the exchange


def test_sql_sharded_mv_matches_single_shard():
    """streaming_parallelism plans the same MV over the 8-device mesh."""
    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig

    def build(par):
        eng = Engine(PlannerConfig(
            chunk_capacity=128, agg_table_size=512, agg_emit_capacity=128,
            mv_table_size=512, mv_ring_size=1024,
        ))
        eng.execute(
            "CREATE SOURCE bid (auction BIGINT, price BIGINT, "
            "date_time TIMESTAMP) WITH (connector='nexmark', "
            "nexmark.table='bid')"
        )
        if par:
            eng.execute(f"SET streaming_parallelism = {par}")
        eng.execute(
            "CREATE MATERIALIZED VIEW v AS SELECT auction, count(*) AS n, "
            "max(price) AS hi FROM bid GROUP BY auction"
        )
        return eng

    a = build(0)       # linear
    b = build(8)       # sharded over the virtual mesh
    from risingwave_tpu.stream.sharded import ShardedStreamingJob
    assert isinstance(b.jobs[0], ShardedStreamingJob)

    a.tick(barriers=2, chunks_per_barrier=2)
    # the sharded job consumes n_shards*cap rows per chunk call; align
    # total rows: linear 4*128 = 512 rows = sharded 4 chunk-units / 8
    b.jobs[0].run_chunk()  # 8*128 = 1024 rows in ONE sharded step...
    b.jobs[0].inject_barrier()

    rows_a = a.execute("SELECT auction, n, hi FROM v")
    # compare against ground truth for the rows each actually consumed
    import numpy as np
    from risingwave_tpu.connector.nexmark import NexmarkGenerator
    def want(total):
        g = NexmarkGenerator()
        _, cols, _ = g.gen_bids(0, total).to_host()
        out = {}
        for auc, pr in zip(cols[0], cols[2]):
            n, hi = out.get(int(auc), (0, 0))
            out[int(auc)] = (n + 1, max(hi, int(pr)))
        return out
    got_a = {int(r[0]): (int(r[1]), int(r[2])) for r in rows_a}
    assert got_a == want(512)
    rows_b = b.execute("SELECT auction, n, hi FROM v")
    got_b = {int(r[0]): (int(r[1]), int(r[2])) for r in rows_b}
    assert got_b == want(1024)
    assert b.jobs[0].committed_epoch > 0


def test_two_phase_partial_agg_unit():
    """PartialAgg collapses duplicate keys; global combine is exact."""
    import jax.numpy as jnp
    from collections import Counter
    from risingwave_tpu.common.chunk import Chunk
    from risingwave_tpu.expr.agg import AggCall, count_star
    from risingwave_tpu.expr.node import InputRef, col
    from risingwave_tpu.stream.fragment import Fragment
    from risingwave_tpu.stream.hash_agg import HashAggExecutor
    from risingwave_tpu.stream.partial_agg import (
        PartialAggExecutor,
        translated_global_calls,
    )

    schema = Schema.of(("g", DataType.INT64), ("v", DataType.INT64))
    group_by = [("g", col("g"))]
    aggs = [count_star("n"), AggCall("sum", col("v"), "s"),
            AggCall("max", col("v"), "hi")]
    partial = PartialAggExecutor(schema, group_by, aggs)
    st, out = Fragment([partial]).step(
        Fragment([partial]).init_states(),
        Chunk.from_pretty("""
            I I
            + 1 10
            + 1 5
            + 2 7
            + 1 1
            + 2 3
        """, names=["g", "v"]),
    )
    rows = sorted(out.to_rows())
    # 5 input rows collapse to 2 partial rows
    assert rows == [(0, 1, 3, 16, 10), (0, 2, 2, 10, 7)]

    glob = HashAggExecutor(
        partial.out_schema,
        [("g", InputRef(0))],
        translated_global_calls(aggs, 1),
        table_size=64, emit_capacity=16,
    )
    frag = Fragment([glob])
    gst = frag.init_states()
    gst, _ = frag.step(gst, out)
    gst, outs = frag.flush(gst, 1)
    mv = Counter()
    for op, *vals in outs[0].to_rows():
        mv[tuple(vals)] += 1 if op in (0, 3) else -1
    assert +mv == Counter({(1, 3, 16, 10): 1, (2, 2, 10, 7): 1})


NEXMARK_WM_SOURCES = """
CREATE SOURCE person (
    id BIGINT, name VARCHAR, date_time TIMESTAMP,
    WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
) WITH (connector = 'nexmark', nexmark.table = 'person',
        nexmark.event.rate = '2000');
CREATE SOURCE auction (
    id BIGINT, seller BIGINT, reserve BIGINT, expires TIMESTAMP,
    date_time TIMESTAMP,
    WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
) WITH (connector = 'nexmark', nexmark.table = 'auction',
        nexmark.event.rate = '2000');
"""

Q8_MV = """
CREATE MATERIALIZED VIEW v AS
SELECT p.id AS id, p.name AS name, a.reserve AS reserve
FROM TUMBLE(person, date_time, INTERVAL '1' SECOND) p
JOIN TUMBLE(auction, date_time, INTERVAL '1' SECOND) a
ON p.id = a.seller AND p.window_start = a.window_start;
"""


def _windowed_engine(par, rate="1000"):
    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig

    eng = Engine(PlannerConfig(
        chunk_capacity=128, agg_table_size=512, agg_emit_capacity=128,
        mv_table_size=512, mv_ring_size=2048,
    ))
    eng.execute(
        "CREATE SOURCE bid (auction BIGINT, price BIGINT, "
        "date_time TIMESTAMP, "
        "WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND) "
        "WITH (connector='nexmark', nexmark.table='bid', "
        f"nexmark.event.rate='{rate}')"
    )
    if par:
        eng.execute(f"SET streaming_parallelism = {par}")
    eng.execute(
        "CREATE MATERIALIZED VIEW v AS SELECT window_start, "
        "max(price) AS hi, count(*) AS n "
        "FROM TUMBLE(bid, date_time, INTERVAL '2' SECOND) "
        "GROUP BY window_start"
    )
    return eng


def test_sharded_windowed_agg_matches_linear():
    """q7-shaped: TUMBLE + GROUP BY window_start runs vnode-sharded
    with watermark cleaning (round-2 verdict item 3a/3c)."""
    from risingwave_tpu.stream.sharded import ShardedStreamingJob

    b = _windowed_engine(8)
    assert isinstance(b.jobs[0], ShardedStreamingJob)
    for _ in range(6):
        b.jobs[0].run_chunk()
        b.jobs[0].inject_barrier()
    a = _windowed_engine(0)
    for _ in range(6 * 8):
        a.jobs[0].run_chunk()
        a.jobs[0].inject_barrier()
    rows_a = a.execute("SELECT window_start, hi, n FROM v ORDER BY window_start")
    rows_b = b.execute("SELECT window_start, hi, n FROM v ORDER BY window_start")
    assert rows_a == rows_b and len(rows_a) > 2


def test_sharded_windowed_agg_state_stays_bounded():
    """50+ barriers: the sharded agg's occupied groups must not grow
    (watermark cleaning evicts closed windows — sharded.py round-2 gap)."""
    eng = _windowed_engine(8, rate="4000")
    job = eng.jobs[0]
    occupied_counts = []
    for i in range(55):
        job.run_chunk()
        job.inject_barrier()
        if i % 10 == 9:
            for s in job.states:
                if hasattr(s, "table"):
                    occupied_counts.append(
                        int(np.asarray(jax.device_get(
                            s.table.occupied)).sum())
                    )
                    break
    # live windows = window_size + wm lag worth, NOT all history
    assert occupied_counts[-1] <= occupied_counts[0] + 4, occupied_counts
    assert max(occupied_counts) < 64, occupied_counts


def test_sharded_join_q8_matches_linear():
    """q8-shaped sharded DAG: join inputs exchange by equi keys inside
    shard_map; results must equal the linear run (verdict item 3d)."""
    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig
    from risingwave_tpu.stream.dag import DagJob

    def build(par):
        eng = Engine(PlannerConfig(
            chunk_capacity=128,
            join_left_table_size=1 << 12, join_left_bucket_cap=4,
            join_right_table_size=1 << 10, join_right_bucket_cap=512,
            join_out_capacity=1 << 12,
            mv_table_size=4096, mv_ring_size=1 << 15,
        ))
        eng.execute(NEXMARK_WM_SOURCES)
        if par:
            eng.execute(f"SET streaming_parallelism = {par}")
        eng.execute(Q8_MV)
        return eng

    b = build(8)
    assert isinstance(b.jobs[0], DagJob) and b.jobs[0].mesh is not None
    for _ in range(6):
        b.jobs[0].chunk_round()
        b.jobs[0].inject_barrier()
    a = build(0)
    for _ in range(6 * 8):
        a.jobs[0].chunk_round()
        a.jobs[0].inject_barrier()
    rows_a = sorted(a.execute("SELECT id, name, reserve FROM v"))
    rows_b = sorted(b.execute("SELECT id, name, reserve FROM v"))
    assert rows_a == rows_b and len(rows_a) > 1000


def test_mv_on_mv_over_sharded_join_matches_linear():
    """ROADMAP carry from round 6 (ISSUE 5 satellite): MV-on-MV over a
    sharded join job no longer raises in ``_ensure_dag`` — a
    per-key-safe chain (project/filter/materialize) attaches PER-SHARD
    inside the upstream's shard_map, backfills the existing rows, and
    matches the linear run; shapes that would merge rows across shards
    still raise the explicit 'next round' error."""
    import pytest

    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig
    from risingwave_tpu.stream.dag import DagJob

    def build(par):
        eng = Engine(PlannerConfig(
            chunk_capacity=128,
            join_left_table_size=1 << 12, join_left_bucket_cap=4,
            join_right_table_size=1 << 10, join_right_bucket_cap=512,
            join_out_capacity=1 << 12,
            mv_table_size=4096, mv_ring_size=1 << 15,
        ))
        eng.execute(NEXMARK_WM_SOURCES)
        if par:
            eng.execute(f"SET streaming_parallelism = {par}")
        eng.execute(Q8_MV)
        return eng

    b = build(8)
    assert isinstance(b.jobs[0], DagJob) and b.jobs[0].mesh is not None
    for _ in range(2):
        b.jobs[0].chunk_round()
        b.jobs[0].inject_barrier()
    # attach mid-stream: existing rows backfill, new rows stream in
    b.execute("CREATE MATERIALIZED VIEW v2 AS "
              "SELECT id, name FROM v WHERE id % 2 = 0")
    assert len(b.jobs) == 1  # attached to the mesh job, not a new one
    for _ in range(2):
        b.jobs[0].chunk_round()
        b.jobs[0].inject_barrier()
    rows_b = sorted(b.execute("SELECT id, name FROM v2"))

    a = build(0)
    for _ in range(2 * 8):
        a.jobs[0].chunk_round()
        a.jobs[0].inject_barrier()
    a.execute("CREATE MATERIALIZED VIEW v2 AS "
              "SELECT id, name FROM v WHERE id % 2 = 0")
    for _ in range(2 * 8):
        a.jobs[0].chunk_round()
        a.jobs[0].inject_barrier()
    rows_a = sorted(a.execute("SELECT id, name FROM v2"))
    assert rows_a == rows_b and len(rows_a) > 500

    # shapes that would pull a NEW un-sharded source into the mesh
    # job keep the explicit error (aggs/joins/TopN attach via the
    # device exchange now — see the cross-shard matrix tests below)
    from risingwave_tpu.sql.engine import PlanError
    with pytest.raises(PlanError, match="next round"):
        b.execute(
            "CREATE MATERIALIZED VIEW vx AS SELECT v.id AS id "
            "FROM v JOIN TUMBLE(person, date_time, INTERVAL '1' "
            "SECOND) p2 ON v.id = p2.id"
        )


def test_sharded_join_recovers_from_checkpoint(tmp_path):
    """Kill-and-recover a sharded join job from the durable store."""
    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig

    def build():
        eng = Engine(PlannerConfig(
            chunk_capacity=128,
            join_left_table_size=1 << 12, join_left_bucket_cap=4,
            join_right_table_size=1 << 10, join_right_bucket_cap=512,
            join_out_capacity=1 << 12,
            mv_table_size=4096, mv_ring_size=1 << 15,
        ), data_dir=str(tmp_path))
        eng.execute(NEXMARK_WM_SOURCES)
        eng.execute("SET streaming_parallelism = 8")
        eng.execute(Q8_MV)
        return eng

    eng = build()
    job = eng.jobs[0]
    for _ in range(4):
        job.chunk_round()
        job.inject_barrier()
    # mesh jobs ride the async checkpoint pipeline now: committed
    # advances on uploader ack, so settle the queue before reading it
    job.drain_uploads()
    want = sorted(eng.execute("SELECT id, name, reserve FROM v"))
    committed = job.committed_epoch

    # per-shard shadow feeds the delta store: after the first full,
    # saves are dirty-fraction DELTAS, not tree-size full copies
    store = eng.checkpoint_store
    kinds = [store.checkpoint_kind("v", e) for e in store.epochs("v")]
    assert "delta" in kinds, kinds
    assert job._shadow is not None and job._shadow.shard_rows == 8

    # simulate mid-epoch crash: extra uncommitted work, then recover
    job.chunk_round()
    job.recover()
    assert job.committed_epoch == committed
    got = sorted(eng.execute("SELECT id, name, reserve FROM v"))
    assert got == want

    # continue after recovery: replay converges with an undisturbed run
    job.chunk_round()
    job.inject_barrier()
    after = sorted(eng.execute("SELECT id, name, reserve FROM v"))
    assert len(after) >= len(want)


def test_partial_agg_nullable_cols():
    """NCol group keys + args through the two-phase partial agg
    (round-2 verdict item 3b): NULL keys form one group; NULL args are
    skipped; an all-NULL segment yields a NULL partial."""
    from collections import Counter
    from risingwave_tpu.common.chunk import Chunk
    from risingwave_tpu.common.types import Field
    from risingwave_tpu.expr.agg import AggCall, count_star
    from risingwave_tpu.expr.node import InputRef, col
    from risingwave_tpu.stream.fragment import Fragment
    from risingwave_tpu.stream.hash_agg import HashAggExecutor
    from risingwave_tpu.stream.partial_agg import (
        PartialAggExecutor,
        translated_global_calls,
    )

    schema = Schema((
        Field("g", DataType.INT64, nullable=True),
        Field("v", DataType.INT64, nullable=True),
    ))
    group_by = [("g", col("g"))]
    aggs = [count_star("rows"), AggCall("count", col("v"), "n"),
            AggCall("sum", col("v"), "s"), AggCall("max", col("v"), "hi")]
    partial = PartialAggExecutor(schema, group_by, aggs)
    assert partial.out_schema[0].nullable          # key passthrough
    assert not partial.out_schema[1].nullable      # count_star
    assert partial.out_schema[3].nullable          # sum over nullable

    chunk = Chunk.from_pretty("""
        I I
        + 1 10
        + 1 .
        + . 7
        + . .
        + 2 .
    """, names=["g", "v"])
    frag = Fragment([partial])
    _, out = frag.step(frag.init_states(), chunk)

    glob = HashAggExecutor(
        partial.out_schema,
        [("g", InputRef(0))],
        translated_global_calls(aggs, 1),
        table_size=64, emit_capacity=16,
    )
    gfrag = Fragment([glob])
    gst = gfrag.init_states()
    gst, _ = gfrag.step(gst, out)
    gst, outs = gfrag.flush(gst, 1)
    mv = Counter()
    for op, *vals in outs[0].to_rows():
        mv[tuple(vals)] += 1 if op in (0, 3) else -1
    # group 1: 2 rows, count(v)=1, sum=10, max=10
    # group NULL: 2 rows, count(v)=1, sum=7, max=7
    # group 2: 1 row, count(v)=0, sum=NULL, max=NULL
    assert +mv == Counter({
        (1, 2, 1, 10, 10): 1,
        (None, 2, 1, 7, 7): 1,
        (2, 1, 0, None, None): 1,
    })


def test_sharded_exchange_carries_ncol():
    """NCol columns survive the all_to_all; NULL keys route to ONE
    shard (grouping-equality vnode routing)."""
    from jax.sharding import PartitionSpec as P
    from risingwave_tpu.common.chunk import NCol
    from risingwave_tpu.common.types import Field
    from risingwave_tpu.parallel.exchange import shuffle_chunk

    from risingwave_tpu.parallel.exchange import shard_map_nocheck

    schema = Schema((
        Field("g", DataType.INT64, nullable=True),
        Field("v", DataType.INT64),
    ))
    mesh = make_mesh(8)
    cap = 16

    def body(_):
        g = NCol(
            jnp.arange(cap, dtype=jnp.int64) % 4,
            jnp.arange(cap) % 4 == 3,  # every 4th row: NULL key
        )
        chunk = Chunk(
            (g, jnp.arange(cap, dtype=jnp.int64)),
            jnp.zeros((cap,), jnp.int8),
            jnp.ones((cap,), jnp.bool_),
            schema,
        )
        out = shuffle_chunk(chunk, [chunk.column(0)], "shard", 8)
        return jax.tree.map(lambda x: x[None], out)

    f = jax.jit(shard_map_nocheck(
        body, mesh=mesh, in_specs=(P("shard"),), out_specs=P("shard"),
    ))
    out = f(jnp.zeros((8,), jnp.int32))
    leaves = jax.tree.map(np.asarray, out)
    null_shards = set()
    total = 0
    for shard in range(8):
        c = jax.tree.map(lambda x: x[shard], leaves)
        _, cols, valid = c.to_host()
        for i in range(int(np.asarray(valid).sum())):
            if cols[0][i] is None:
                null_shards.add(shard)
            total += 1
    assert total == 8 * cap            # nothing lost
    assert len(null_shards) == 1       # NULL keys on exactly one shard


def test_sql_sharded_global_topn_matches_linear():
    """GROUP BY + ORDER BY/LIMIT plans sharded: per-shard bands hold a
    superset of the global top-k and the serving read applies the
    global order+limit (r3 verdict ask #8 — q4/q6-shaped plans stop
    falling back to linear)."""
    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig

    SQL = ("CREATE MATERIALIZED VIEW v AS SELECT auction, count(*) AS n "
           "FROM bid GROUP BY auction ORDER BY n DESC, auction LIMIT 5")

    def build(par):
        eng = Engine(PlannerConfig(
            chunk_capacity=128, agg_table_size=512, agg_emit_capacity=128,
            mv_table_size=512, mv_ring_size=1024,
            topn_pool_size=512, topn_emit_capacity=128,
        ))
        eng.execute(
            "CREATE SOURCE bid (auction BIGINT, price BIGINT, "
            "date_time TIMESTAMP) WITH (connector='nexmark', "
            "nexmark.table='bid')"
        )
        if par:
            eng.execute(f"SET streaming_parallelism = {par}")
        eng.execute(SQL)
        return eng

    from risingwave_tpu.stream.sharded import ShardedStreamingJob
    a = build(0)
    b = build(8)
    assert isinstance(b.jobs[0], ShardedStreamingJob), \
        "global TopN should shard now"

    # equal row counts: linear 8 chunks of 128 = sharded 1 step of 8x128
    a.tick(barriers=1, chunks_per_barrier=8)
    b.jobs[0].run_chunk()
    b.jobs[0].inject_barrier()

    got_a = a.execute("SELECT auction, n FROM v")
    got_b = b.execute("SELECT auction, n FROM v")
    # band CONTENT matches (linear serving returns band rows unordered;
    # the sharded read merges + orders via serving_topn)
    assert sorted(tuple(map(int, r)) for r in got_a) == \
        sorted(tuple(map(int, r)) for r in got_b)
    assert len(got_b) == 5
    # and the band is the true top-5 (ground truth)
    from risingwave_tpu.connector.nexmark import NexmarkGenerator
    g = NexmarkGenerator()
    _, cols, _ = g.gen_bids(0, 1024).to_host()
    import collections
    cnt = collections.Counter(int(x) for x in cols[0])
    want = sorted(cnt.items(), key=lambda kv: (-kv[1], kv[0]))[:5]
    assert [tuple(map(int, r)) for r in got_b] == want


def test_online_rescale_2_to_4_converges():
    """ALTER MATERIALIZED VIEW ... SET PARALLELISM mid-stream: state
    moves to the new mesh at a barrier and results converge with an
    undisturbed run (r3 verdict ask #7; ref scale.rs reschedule)."""
    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig

    def build(par):
        eng = Engine(PlannerConfig(
            chunk_capacity=128, agg_table_size=512, agg_emit_capacity=128,
            mv_table_size=512, mv_ring_size=1024,
        ))
        eng.execute(
            "CREATE SOURCE bid (auction BIGINT, price BIGINT, "
            "date_time TIMESTAMP) WITH (connector='nexmark', "
            "nexmark.table='bid')"
        )
        eng.execute(f"SET streaming_parallelism = {par}")
        eng.execute(
            "CREATE MATERIALIZED VIEW v AS SELECT auction, "
            "count(*) AS n, max(price) AS hi FROM bid GROUP BY auction"
        )
        return eng

    eng = build(2)
    from risingwave_tpu.stream.sharded import ShardedStreamingJob
    job = eng.jobs[0]
    assert isinstance(job, ShardedStreamingJob)
    assert job.sharded.n_shards == 2

    # phase 1 on 2 shards: 2 chunk-units = 2*2*128 = 512 rows
    job.run_chunk(); job.run_chunk(); job.inject_barrier()
    eng.execute("ALTER MATERIALIZED VIEW v SET PARALLELISM 4")
    assert job.sharded.n_shards == 4
    mid = {int(r[0]): (int(r[1]), int(r[2]))
           for r in eng.execute("SELECT auction, n, hi FROM v")}

    # phase 2 on 4 shards: 1 chunk-unit = 4*128 = 512 rows
    job.run_chunk(); job.inject_barrier()
    got = {int(r[0]): (int(r[1]), int(r[2]))
           for r in eng.execute("SELECT auction, n, hi FROM v")}

    from risingwave_tpu.connector.nexmark import NexmarkGenerator

    def want(total):
        g = NexmarkGenerator()
        _, cols, _ = g.gen_bids(0, total).to_host()
        out = {}
        for auc, pr in zip(cols[0], cols[2]):
            n, hi = out.get(int(auc), (0, 0))
            out[int(auc)] = (n + 1, max(hi, int(pr)))
        return out

    assert mid == want(512), "state lost/duplicated across rescale"
    assert got == want(1024), "post-rescale stream diverged"


def test_sharded_sink_delivers_exactly_once_across_recovery():
    """A sharded agg job with a file sink: per-shard ring cursors merge
    at the snapshot barrier; recovery neither duplicates nor drops
    (r3 verdict ask #8, sink half)."""
    import json as _json

    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig

    import tempfile, os
    tmp = tempfile.mkdtemp()
    path = os.path.join(tmp, "out.jsonl")
    data_dir = os.path.join(tmp, "ckpt")

    def build():
        eng = Engine(PlannerConfig(
            chunk_capacity=128, agg_table_size=512, agg_emit_capacity=128,
            mv_table_size=512, mv_ring_size=2048,
        ), data_dir=data_dir)
        eng.execute(
            "CREATE SOURCE bid (auction BIGINT, price BIGINT, "
            "date_time TIMESTAMP) WITH (connector='nexmark', "
            "nexmark.table='bid')"
        )
        eng.execute("SET streaming_parallelism = 4")
        eng.execute(
            "CREATE SINK s AS SELECT auction, count(*) AS n FROM bid "
            f"GROUP BY auction WITH (connector='file', path='{path}')"
        )
        return eng

    eng = build()
    from risingwave_tpu.stream.sharded import ShardedStreamingJob
    job = eng.jobs[0]
    assert isinstance(job, ShardedStreamingJob), "sink job should shard"
    job.run_chunk()
    job.inject_barrier()

    # fold the delivered changelog: per-key latest insert wins
    def fold():
        state = {}
        for line in open(path):
            r = _json.loads(line)
            if r["op"] in ("insert", "update_insert"):
                state[r["auction"]] = r["n"]
            elif r["op"] in ("delete", "update_delete"):
                state.pop(r["auction"], None)
        return state

    from risingwave_tpu.connector.nexmark import NexmarkGenerator
    import collections
    g = NexmarkGenerator()
    _, cols, _ = g.gen_bids(0, 512).to_host()
    want1 = dict(collections.Counter(int(x) for x in cols[0]))
    assert fold() == want1

    # crash + recover: the fresh engine cold-starts from data_dir
    # (DDL replay + checkpoint restore) and resumes delivery
    eng2 = Engine(PlannerConfig(
        chunk_capacity=128, agg_table_size=512, agg_emit_capacity=128,
        mv_table_size=512, mv_ring_size=2048,
    ), data_dir=data_dir)
    job2 = eng2.jobs[0]
    job2.run_chunk()
    job2.inject_barrier()
    _, cols, _ = g.gen_bids(0, 1024).to_host()
    want2 = dict(collections.Counter(int(x) for x in cols[0]))
    assert fold() == want2, "duplicated or lost sink rows after recovery"


def test_rescale_survives_recovery_with_stale_ddl_parallelism():
    """A rescaled job's checkpoint is authoritative: recovery rebuilds
    the mesh to the checkpoint's shard dim even when the replanned DDL
    asked for the old parallelism."""
    import tempfile
    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig

    data_dir = tempfile.mkdtemp()

    def build():
        eng = Engine(PlannerConfig(
            chunk_capacity=128, agg_table_size=512, agg_emit_capacity=128,
            mv_table_size=512, mv_ring_size=1024,
        ), data_dir=data_dir)
        eng.execute(
            "CREATE SOURCE bid (auction BIGINT, price BIGINT, "
            "date_time TIMESTAMP) WITH (connector='nexmark', "
            "nexmark.table='bid')"
        )
        eng.execute("SET streaming_parallelism = 2")
        eng.execute(
            "CREATE MATERIALIZED VIEW v AS SELECT auction, "
            "count(*) AS n FROM bid GROUP BY auction"
        )
        return eng

    eng = build()
    job = eng.jobs[0]
    job.run_chunk()
    job.inject_barrier()
    eng.execute("ALTER MATERIALIZED VIEW v SET PARALLELISM 4")
    want = sorted(map(tuple, eng.execute("SELECT * FROM v")))

    # cold start: bootstrap replays the DDL log (including the ALTER
    # PARALLELISM) and restores the 4-shard checkpoint topology
    eng2 = Engine(PlannerConfig(
        chunk_capacity=128, agg_table_size=512, agg_emit_capacity=128,
        mv_table_size=512, mv_ring_size=1024,
    ), data_dir=data_dir)
    job2 = eng2.jobs[0]
    assert job2.sharded.n_shards == 4, "checkpoint topology not restored"
    assert sorted(map(tuple, eng2.execute("SELECT * FROM v"))) == want


def test_sharded_dag_spill_over_join():
    """Spill-to-host under the mesh (verdict r4 item 5): a sharded
    join→agg job whose group cardinality is ~4x the device table
    completes via PER-SHARD host tiers, matching the linear run."""
    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig
    from risingwave_tpu.stream.dag import DagJob

    n_groups = 220  # >> agg_table_size(64)

    def build(par):
        eng = Engine(PlannerConfig(
            chunk_capacity=128,
            agg_table_size=64,
            agg_emit_capacity=256,
            join_table_size=1 << 10, join_bucket_cap=32,
            join_out_capacity=1 << 12,
            mv_table_size=1 << 10, mv_ring_size=1 << 12,
            agg_spill_ring=1 << 10,
        ))
        if par:
            eng.execute(f"SET streaming_parallelism = {par}")
        eng.execute("CREATE TABLE item (id BIGINT, grp BIGINT, "
                    "PRIMARY KEY (id))")
        eng.execute("CREATE TABLE hit (item BIGINT, w BIGINT)")
        for i in range(0, n_groups, 64):
            vals = ",".join(f"({k},{k % 7})"
                            for k in range(i, min(i + 64, n_groups)))
            eng.execute(f"INSERT INTO item VALUES {vals}")
        rows = [(i, 10 * i + r) for i in range(n_groups)
                for r in range(2)]
        for i in range(0, len(rows), 64):
            vals = ",".join(f"({a},{b})" for a, b in rows[i:i + 64])
            eng.execute(f"INSERT INTO hit VALUES {vals}")
        eng.execute(
            "CREATE MATERIALIZED VIEW mv AS SELECT h.item AS k, "
            "count(*) AS n, sum(h.w) AS s FROM hit h "
            "JOIN item i ON h.item = i.id GROUP BY h.item"
        )
        eng.execute("FLUSH")
        eng.tick(barriers=4)
        return eng

    lin = build(0)
    want = sorted(map(tuple, lin.execute("SELECT * FROM mv")))
    assert len(want) == n_groups

    sh = build(2)
    job = sh.jobs[0]
    assert isinstance(job, DagJob) and job.mesh is not None
    got = sorted(map(tuple, sh.execute("SELECT * FROM mv")))
    assert got == want
    # the device table really was too small: per-shard tiers absorbed
    tiers = getattr(job, "_spill_tiers", {})
    absorbed = sum(t.rows_absorbed for ts in tiers.values() for t in ts)
    assert tiers and absorbed > 0


def _q8_engine(par, extra=None):
    """Shared builder for the cross-shard MV-on-MV matrix tests."""
    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig

    cfg = dict(
        chunk_capacity=128,
        join_left_table_size=1 << 12, join_left_bucket_cap=4,
        join_right_table_size=1 << 10, join_right_bucket_cap=512,
        join_out_capacity=1 << 12,
        mv_table_size=4096, mv_ring_size=1 << 15,
        topn_pool_size=1 << 12, topn_emit_capacity=256,
        agg_table_size=1 << 10, agg_emit_capacity=512,
    )
    cfg.update(extra or {})
    eng = Engine(PlannerConfig(**cfg))
    eng.execute(NEXMARK_WM_SOURCES)
    if par:
        eng.execute(f"SET streaming_parallelism = {par}")
    eng.execute(Q8_MV)
    return eng


def _drive(eng, rounds):
    for _ in range(rounds):
        for job in eng.jobs:
            job.chunk_round()
        for job in eng.jobs:
            job.inject_barrier()


def test_cross_shard_agg_and_topn_over_sharded_join_matches_linear():
    """ISSUE 9 tentpole: previously-rejected cross-shard MV-on-MV
    shapes attach via the device hash exchange and converge
    byte-identical to the linear run, including mid-stream attach +
    backfill:

    - ``vagg``: HashAgg over a REDUCED key (group ``id`` ⊂ the join's
      (id, window) distribution) — exchange keyed on the group-by;
    - ``vcnt``: GLOBAL agg (no keys) — constant-key exchange to one
      owning shard (the singleton-fragment analog);
    - ``vt``: global TopN over the sharded agg MV — constant-key
      exchange, band on one shard, merged read identical."""
    from risingwave_tpu.stream.dag import DagJob

    b = _q8_engine(8)
    assert isinstance(b.jobs[0], DagJob) and b.jobs[0].mesh is not None
    _drive(b, 2)
    b.execute("CREATE MATERIALIZED VIEW vagg AS SELECT id, "
              "count(*) AS n, sum(reserve) AS s FROM v GROUP BY id")
    b.execute("CREATE MATERIALIZED VIEW vcnt AS "
              "SELECT count(*) AS n FROM v")
    b.execute("CREATE MATERIALIZED VIEW vt AS SELECT id, n FROM vagg "
              "ORDER BY n DESC, id LIMIT 5")
    assert len(b.jobs) == 1  # all attached to the one mesh job
    _drive(b, 2)

    a = _q8_engine(0)
    _drive(a, 2 * 8)
    a.execute("CREATE MATERIALIZED VIEW vagg AS SELECT id, "
              "count(*) AS n, sum(reserve) AS s FROM v GROUP BY id")
    a.execute("CREATE MATERIALIZED VIEW vcnt AS "
              "SELECT count(*) AS n FROM v")
    a.execute("CREATE MATERIALIZED VIEW vt AS SELECT id, n FROM vagg "
              "ORDER BY n DESC, id LIMIT 5")
    _drive(a, 2 * 8)

    for mv in ("vagg", "vcnt", "vt"):
        ra = sorted(a.execute(f"SELECT * FROM {mv}"))
        rb = sorted(b.execute(f"SELECT * FROM {mv}"))
        assert ra == rb and len(ra) > 0, (mv, ra[:3], rb[:3])
    # the reduced-key agg really is cross-shard: groups live on more
    # than one shard of the attached agg node
    job = b.jobs[0]
    vagg_node = b.catalog.get("vagg").mv_state_index[0]
    occ = np.asarray(jax.device_get(
        job.states[vagg_node][0].table.occupied))
    shards_with_groups = int((occ.sum(axis=1) > 0).sum())
    assert shards_with_groups > 1, "agg groups all on one shard"


def test_cross_shard_join_of_two_sharded_mvs_matches_linear():
    """Join of two SHARDED MVs: their mesh jobs merge into one, the
    new JoinNode gets an all_to_all exchange per side keyed on its
    equi keys, both sides backfill through the exchange, and the
    result is byte-identical to the linear run."""
    from risingwave_tpu.stream.dag import DagJob

    W_MV = ("CREATE MATERIALIZED VIEW w AS "
            "SELECT a.reserve AS r, a.expires AS exp "
            "FROM TUMBLE(person, date_time, INTERVAL '1' SECOND) p "
            "JOIN TUMBLE(auction, date_time, INTERVAL '1' SECOND) a "
            "ON p.id = a.seller AND p.window_start = a.window_start")
    J_MV = ("CREATE MATERIALIZED VIEW j AS SELECT v.id AS id, "
            "v.reserve AS reserve, w.exp AS exp FROM v JOIN w "
            "ON v.reserve = w.r")

    b = _q8_engine(8, extra={"mv_ring_size": 1 << 16})
    b.execute(W_MV)
    assert all(isinstance(jb, DagJob) and jb.mesh is not None
               for jb in b.jobs)
    assert len(b.jobs) == 2
    _drive(b, 1)
    b.execute(J_MV)  # mid-stream: merges the two mesh jobs
    assert len(b.jobs) == 1
    _drive(b, 1)
    rb = sorted(b.execute("SELECT id, reserve, exp FROM j"))

    a = _q8_engine(0, extra={"mv_ring_size": 1 << 16})
    a.execute(W_MV)
    _drive(a, 1 * 8)
    a.execute(J_MV)
    _drive(a, 1 * 8)
    ra = sorted(a.execute("SELECT id, reserve, exp FROM j"))
    assert ra == rb and len(ra) > 100
