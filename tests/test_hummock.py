"""Hummock-lite storage service: object store, versions, compactor, GC.

Ref: the madsim sim-object-store chaos pattern
(src/object_store/src/object/sim/), compaction off the write path
(compactor_runner.rs:70), version pin/unpin (commit_epoch.rs:73), and
the meta vacuum's orphan-object GC (SURVEY.md §2.5/§3.5)."""

import struct

import pytest

from risingwave_tpu.common.metrics import MetricsRegistry
from risingwave_tpu.storage.hummock import (
    CompactorService,
    HummockStorage,
    InMemObjectStore,
    LocalFsObjectStore,
    ObjectError,
    StoreFaults,
    VersionManager,
)
from risingwave_tpu.storage.hummock.store import SST_PREFIX
from risingwave_tpu.storage.sst import TOMBSTONE


def _k(i: int) -> bytes:
    return struct.pack(">I", i)


# -- object store -------------------------------------------------------
def test_object_store_basics(tmp_path):
    for store in (InMemObjectStore(),
                  LocalFsObjectStore(str(tmp_path / "os"))):
        store.put("a/x", b"1")
        store.put("a/y", b"22")
        store.put("b", b"333")
        assert store.get("a/y") == b"22"
        assert store.size("b") == 3
        assert store.list("a/") == ["a/x", "a/y"]
        assert store.exists("a/x") and not store.exists("nope")
        with store.open("b") as f:
            assert f.read() == b"333"
        store.delete("a/x")
        assert not store.exists("a/x")
        store.delete("a/x")  # idempotent
        with pytest.raises(ObjectError):
            store.get("a/x")
        # overwrite is atomic-replace
        store.put("b", b"4444")
        assert store.get("b") == b"4444"


def test_object_store_fault_injection(tmp_path):
    """Deterministic faults: Nth matching op fails, 'before' loses the
    write, 'after' persists it then raises (crash-after-upload)."""
    for store in (InMemObjectStore(StoreFaults()),
                  LocalFsObjectStore(str(tmp_path / "os"),
                                     StoreFaults())):
        store.faults.fail("put", substr="sst/", mode="before")
        with pytest.raises(ObjectError):
            store.put("sst/001", b"x")
        assert not store.exists("sst/001")       # lost with the crash
        store.put("sst/001", b"x")               # rule retired
        store.faults.fail("put", substr="sst/", after=1, mode="after")
        store.put("sst/002", b"y")               # after=1 skips this
        with pytest.raises(ObjectError):
            store.put("sst/003", b"z")
        assert store.get("sst/003") == b"z"      # durable orphan
        assert store.faults.injected_errors == 2


# -- version manager ----------------------------------------------------
def test_version_manager_replay_pins_and_base_pruning():
    from risingwave_tpu.storage.hummock.version import SstInfo

    store = InMemObjectStore()
    vm = VersionManager(store, base_interval=5)

    def sst(name):
        return SstInfo(key=f"sst/{name}", first_key=b"a", last_key=b"z",
                       n_records=1, size=10)

    for e in range(1, 4):
        vm.commit(e, adds={0: [sst(f"l0_{e}")]}, removes={})
    assert vm.current.vid == 3 and vm.current.l0_depth() == 3
    assert vm.current.max_committed_epoch == 3
    # L0 is newest-first
    assert vm.current.levels[0][0].key == "sst/l0_3"

    pin_id, pinned = vm.pin()
    # a compaction moves everything to L1
    vm.commit(3, adds={1: [sst("l1_a")]},
              removes={0: [s.key for s in vm.current.levels[0]]})
    assert vm.current.l0_depth() == 0
    assert pinned.l0_depth() == 3  # pinned snapshot unaffected
    assert "sst/l0_1" in vm.referenced_keys()  # held by the pin
    vm.unpin(pin_id)
    assert "sst/l0_1" not in vm.referenced_keys()

    # cross the base interval: log gets re-anchored + pruned
    for e in range(4, 8):
        vm.commit(e, adds={0: [sst(f"l0b_{e}")]}, removes={})
    assert store.list("version/base_") != []
    # a fresh manager replays base + tail deltas to the same version
    vm2 = VersionManager(store)
    assert vm2.current.to_json() == vm.current.to_json()


# -- storage: merge-free writes, reads, stall ---------------------------
def test_write_path_is_merge_free_and_reads_correct():
    m = MetricsRegistry()
    h = HummockStorage(InMemObjectStore(), metrics=m, l0_trigger=4)
    model = {}
    for step in range(10):
        pairs = [(_k(i), f"s{step}".encode())
                 for i in range(step, step + 20)]
        h.write_batch(pairs, epoch=step + 1)
        model.update(pairs)
    # ingest NEVER merged: every batch is its own L0 run
    assert h.write_path_merges == 0
    assert h.l0_depth() == 10
    assert h.versions.current.max_committed_epoch == 10
    assert dict(h.scan()) == dict(sorted(model.items()))
    assert h.get(_k(12)) == model[_k(12)]
    assert h.get(_k(999)) is None
    # bloom/range pruning recorded
    assert m.get("storage_bloom_filter_total", result="hit") >= 1


def test_background_compactor_bounds_l0_and_preserves_view():
    h = HummockStorage(InMemObjectStore(), l0_trigger=3,
                       base_bytes=1 << 12, ratio=2, stall_l0=6)
    svc = CompactorService(h, poll_interval_s=0.001).start()
    model = {}
    try:
        for step in range(40):
            pairs = [(_k(i), f"s{step}v{i}".encode())
                     for i in range(step % 5, 50, 2)]
            h.write_batch(pairs, epoch=step)
            model.update(pairs)
            if step % 4 == 0:
                dels = [_k(i) for i in range(step % 7, 14, 3)]
                h.delete_batch(dels, epoch=step)
                for d in dels:
                    model.pop(d, None)
            # the write-stall contract keeps L0 bounded
            h.wait_below_stall(timeout=5.0)
            assert h.l0_depth() <= h.stall_l0
    finally:
        svc.stop()
    svc.drain()
    assert svc.errors == 0
    assert svc.tasks_run > 0
    assert h.write_path_merges == 0  # compaction ONLY in the service
    assert dict(h.scan()) == dict(sorted(model.items()))
    for i in range(50):
        assert h.get(_k(i)) == model.get(_k(i))


def test_write_stall_resolves_via_compactor():
    h = HummockStorage(InMemObjectStore(), l0_trigger=2, stall_l0=3)
    for i in range(4):
        h.write_batch([(_k(i), b"v")])
    assert h.stalled()
    # no compactor: the wait times out but reports the stall
    waited = h.wait_below_stall(timeout=0.05)
    assert waited >= 0.05
    svc = CompactorService(h, poll_interval_s=0.001).start()
    try:
        waited = h.wait_below_stall(timeout=5.0)
        assert not h.stalled()
    finally:
        svc.stop()


def test_pinned_read_survives_compaction_and_vacuum():
    store = InMemObjectStore()
    h = HummockStorage(store, l0_trigger=2, stall_l0=100)
    for step in range(3):
        h.write_batch([(_k(i), f"g{step}".encode())
                       for i in range(step * 4, step * 4 + 8)])
    pv = h.pin()
    before = sorted(pv.scan())
    # compact everything + more ingest + vacuum under the pin
    while h.compact_once():
        pass
    h.write_batch([(_k(100), b"new")])
    h.vacuum()
    live = set(store.list(SST_PREFIX))
    assert all(s.key in live
               for lv in pv.version.levels for s in lv)
    assert sorted(pv.scan()) == before  # consistent SST set under pin
    pv.release()
    h.vacuum()
    # now the store holds exactly the live referenced set
    assert set(store.list(SST_PREFIX)) == h.versions.referenced_keys()


# -- crash recovery -----------------------------------------------------
def test_crash_mid_compaction_replays_consistent_and_gc_orphans():
    """Kill the compactor between output upload and delta commit: the
    reopened version log must replay to the pre-crash SST set and the
    orphaned upload must be vacuumed."""
    store = InMemObjectStore()
    h = HummockStorage(store, l0_trigger=2, stall_l0=100)
    model = {}
    for step in range(4):
        pairs = [(_k(i), f"s{step}".encode()) for i in range(12)]
        h.write_batch(pairs, epoch=step + 1)
        model.update(pairs)
    task = h.pick_compaction()
    assert task is not None
    h.execute_compaction(task)   # output SST uploaded...
    assert task.outputs
    orphan = task.outputs[0].key
    assert store.exists(orphan)
    del h                        # ...and the process dies before commit

    h2 = HummockStorage(store, l0_trigger=2, stall_l0=100)
    # replayed version: all four L0 runs, view intact
    assert h2.l0_depth() == 4
    assert dict(h2.scan()) == dict(sorted(model.items()))
    assert orphan not in h2.versions.referenced_keys()
    deleted = h2.vacuum()
    assert deleted >= 1 and not store.exists(orphan)
    # and compaction picks up where the dead compactor left off
    while h2.compact_once():
        pass
    assert dict(h2.scan()) == dict(sorted(model.items()))
    # allocator never hands out an id that could alias a live object
    assert h2._next_sst > int(orphan[len(SST_PREFIX):-4])


def test_compactor_service_survives_injected_upload_faults():
    """A lost output upload (fault 'before') errors the task; the
    service stays alive, retries, and converges once the fault clears.
    A durable-then-crash upload (fault 'after') leaves an orphan that
    vacuum reaps."""
    faults = StoreFaults()
    store = InMemObjectStore(faults)
    h = HummockStorage(store, l0_trigger=3, stall_l0=100)
    model = {}
    for step in range(6):
        pairs = [(_k(i), f"s{step}".encode()) for i in range(20)]
        h.write_batch(pairs, epoch=step)
        model.update(pairs)
    n_objects = len(store.list(SST_PREFIX))

    # compactor outputs are the next sst/ puts — fail two of them, one
    # lost, one durable-but-uncommitted
    faults.fail("put", substr=SST_PREFIX, mode="before")
    faults.fail("put", substr=SST_PREFIX, mode="after")
    svc = CompactorService(h, poll_interval_s=0.001).start()
    try:
        import time
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            # converged = both faults consumed, at least one task
            # committed, nothing due, nothing in flight
            if (faults.injected_errors >= 2 and svc.tasks_run >= 1
                    and not h._busy_levels
                    and h.pending_compaction_level() is None):
                break
            time.sleep(0.005)
    finally:
        svc.stop()
    assert svc.errors >= 2          # the injected failures were seen
    assert h.pending_compaction_level() is None  # ...but it converged
    assert dict(h.scan()) == dict(sorted(model.items()))
    # the 'after'-mode orphan (durable upload, no commit) gets GC'd
    h.vacuum()
    live = set(store.list(SST_PREFIX))
    assert live == h.versions.referenced_keys()
    assert len(live) < n_objects    # compaction really shrank the set


def test_crash_mid_ingest_orphan_gc(tmp_path):
    """write_batch dying between upload and commit (fault 'after'):
    reopen sees the pre-crash version; the orphan is vacuumed.  Runs on
    the LocalFs store to cover the filesystem backend."""
    faults = StoreFaults()
    store = LocalFsObjectStore(str(tmp_path / "os"), faults)
    h = HummockStorage(store, stall_l0=100)
    h.write_batch([(_k(1), b"a")], epoch=1)
    faults.fail("put", substr=SST_PREFIX, mode="after")
    with pytest.raises(ObjectError):
        h.write_batch([(_k(2), b"b")], epoch=2)
    del h
    h2 = HummockStorage(store, stall_l0=100)
    assert dict(h2.scan()) == {_k(1): b"a"}
    assert h2.versions.current.max_committed_epoch == 1
    assert h2.vacuum() == 1      # the uncommitted upload
    assert set(store.list(SST_PREFIX)) == h2.versions.referenced_keys()


# -- engine + ctl wiring ------------------------------------------------
def _mk_engine(tmp_path):
    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig

    eng = Engine(PlannerConfig(
        chunk_capacity=64, agg_table_size=256, agg_emit_capacity=64,
        mv_table_size=256, mv_ring_size=1024,
    ), data_dir=str(tmp_path / "data"))
    eng.execute("""
        CREATE SOURCE t (k BIGINT, v BIGINT) WITH (connector='datagen');
        CREATE MATERIALIZED VIEW m AS
        SELECT k % 4 AS g, count(*) AS n FROM t GROUP BY k % 4;
    """)
    return eng


def test_engine_mv_export_and_pinned_serving(tmp_path):
    eng = _mk_engine(tmp_path)
    eng.tick(barriers=2, chunks_per_barrier=1)
    live = sorted(map(tuple, eng.execute("SELECT g, n FROM m")))
    info = eng.storage_export_mv("m")
    assert info["rows"] == len(live) and info["deletes"] == 0
    got = sorted((int(a), int(b)) for a, b in eng.storage_serve_mv("m"))
    assert got == [(int(a), int(b)) for a, b in live]

    # the MV changes; a re-export writes upserts + tombstones and the
    # serving read tracks it (through a NEW pinned version)
    eng.tick(barriers=2, chunks_per_barrier=1)
    live2 = sorted(map(tuple, eng.execute("SELECT g, n FROM m")))
    eng.storage_export_mv("m")
    got2 = sorted((int(a), int(b)) for a, b in eng.storage_serve_mv("m"))
    assert got2 == [(int(a), int(b)) for a, b in live2]
    assert got2 != got

    # compaction + vacuum do not disturb serving
    while eng.hummock.compact_once():
        pass
    eng.storage_vacuum()
    got3 = sorted((int(a), int(b)) for a, b in eng.storage_serve_mv("m"))
    assert got3 == got2


def test_engine_stall_hook_and_ctl_storage_commands(tmp_path):
    from risingwave_tpu import ctl

    eng = _mk_engine(tmp_path)
    eng.tick(barriers=1, chunks_per_barrier=1)
    # tick wires the barrier loop's write-stall hook to storage
    assert eng.jobs[0].write_stall_hook is not None
    info = ctl.storage_info(eng)
    assert info["enabled"] and info["version_id"] >= 0
    assert info["compactor"]["running"] is False
    # force a stall: tiny threshold, then tick must stall (timeout
    # bounded) and record stall seconds
    eng.hummock.stall_l0 = 1
    for i in range(2):
        eng.hummock.write_batch([(_k(i), b"x")])
    t = eng.jobs[0]
    before = t.stall_seconds
    eng.hummock.wait_below_stall = lambda timeout=5.0: 0.25  # stub wait
    eng.tick(barriers=1, chunks_per_barrier=1)
    assert eng.jobs[0].stall_seconds >= before + 0.25

    # ctl storage gc deletes nothing while everything is referenced
    res = ctl.storage_gc(eng)
    assert res["deleted_objects"] == 0
    # drop the L0 runs via compaction, then gc reclaims the inputs
    eng.hummock.stall_l0 = 100
    eng.hummock.l0_trigger = 2
    while eng.hummock.compact_once():
        pass
    res = ctl.storage_gc(eng)
    assert res["deleted_objects"] >= 1
    assert ctl.cluster_info(eng)["storage"]["enabled"]


def test_engine_storage_service_background(tmp_path):
    """Engine-owned compactor thread: sustained ingest through the
    engine's storage facade stays bounded and serves correctly."""
    eng = _mk_engine(tmp_path)
    eng.hummock.l0_trigger = 3
    eng.hummock.stall_l0 = 6
    eng.start_storage_service()
    try:
        model = {}
        for step in range(25):
            pairs = [(_k(i), f"s{step}".encode())
                     for i in range(step % 3, 30, 2)]
            eng.hummock.write_batch(pairs, epoch=step)
            model.update(pairs)
            eng.hummock.wait_below_stall(timeout=5.0)
            assert eng.hummock.l0_depth() <= eng.hummock.stall_l0
    finally:
        eng.stop_storage_service()
    eng.compactor.drain()
    assert dict(eng.hummock.scan()) == dict(sorted(model.items()))
    assert eng.hummock.write_path_merges == 0


# -- serving pin leases vs vacuum (ISSUE 5 satellite) -------------------
def test_stale_serving_lease_reaped_unblocks_gc(tmp_path):
    """A serving replica's epoch pin lease holds its SST set in the
    vacuum keep-set; a STALE lease (dead replica, expired heartbeat)
    is reaped by the meta so it can never block GC forever."""
    import time

    from risingwave_tpu.cluster import MetaService
    from risingwave_tpu.serve import ServingWorker

    meta = MetaService(str(tmp_path), heartbeat_timeout_s=0.2)
    meta.start(port=0, monitor=False, compactor=False)
    sv = None
    try:
        # seed data so the replica's first lease pins a real SST set
        meta.hummock.write_batch(
            [(_k(i), b"v0") for i in range(32)], epoch=1
        )
        addr = f"127.0.0.1:{meta.rpc_port}"
        # NO heartbeat thread: the lease goes stale on its own
        sv = ServingWorker(addr, str(tmp_path))
        sv.start(heartbeat=False)
        assert meta.versions.pinned_count() >= 1
        pinned_keys = set(sv.view.version.all_keys())
        assert pinned_keys

        # churn: the pinned SSTs leave the current version...
        for step in range(4):
            meta.hummock.write_batch(
                [(_k(i), f"v{step + 1}".encode())
                 for i in range(32)], epoch=step + 2,
            )
        while meta.hummock.compact_once():
            pass
        assert not pinned_keys <= meta.versions.current.all_keys()
        # ...but the live lease keeps them on disk
        meta.storage_vacuum()
        for key in pinned_keys:
            assert meta.hummock.store.exists(key), key
        # and the pinned read still answers
        assert sv.view.point_get(_k(3)) == b"v0"

        # lease expires (no heartbeats) → meta reaps it → GC proceeds
        time.sleep(0.3)
        meta.check_heartbeats()
        assert meta.state()["serving"] == []
        assert meta.versions.pinned_count() == 0
        res = meta.storage_vacuum()
        assert res["deleted_objects"] >= 1
        assert not any(meta.hummock.store.exists(k)
                       for k in pinned_keys)
    finally:
        if sv is not None:
            sv.stop()
        meta.stop()


# -- stress (short version of scripts/compaction_stress.py) -------------
@pytest.mark.slow
def test_compaction_stress_short():
    import importlib
    import sys

    sys.path.insert(0, "scripts")
    try:
        stress = importlib.import_module("compaction_stress")
    finally:
        sys.path.pop(0)
    summary = stress.run(seconds=3.0, batch_rows=64, key_space=2000,
                         stall_l0=8, l0_trigger=3)
    assert summary["read_errors"] == 0
    assert summary["max_l0_observed"] <= summary["stall_l0"]
    assert summary["write_path_merges"] == 0
    assert summary["verified_rows"] > 0
