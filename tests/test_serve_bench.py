"""Slow pytest wrapper for scripts/serve_bench.py (ISSUE 5 satellite):
sustained concurrent serving reads during ingest — throughput floor,
post-warmup block-cache hit-ratio floor, replica carries the reads,
and ZERO errors while compaction + vacuum churn underneath."""

import importlib
import sys

import pytest


@pytest.mark.slow
def test_serve_bench_short():
    sys.path.insert(0, "scripts")
    try:
        bench = importlib.import_module("serve_bench")
    finally:
        sys.path.pop(0)
    summary = bench.run(seconds=4.0, readers=2)
    bad = bench.check(summary, min_reads_per_s=10.0,
                      min_hit_ratio=0.5, min_replica_share=0.5)
    assert bad == [], (bad, summary)
    assert summary["rounds_committed"] >= 1
