"""Slow pytest wrapper for scripts/serve_bench.py (ISSUE 10
satellite): the batched/cached serving workload during ingest —
throughput + p99.9 latency floors, post-warmup block- AND
result-cache hit-ratio floors, ZERO errors through a replica
hard-kill, ZERO stale rows through the epoch-advance invalidation
probe, and the secondary index byte-identical to (and faster than)
the full scan.

Floors here are deliberately conservative vs the CLI defaults (the
1-core CI box runs the suite, not a quiet bench window; the 10k
reads/s acceptance number is asserted by a standalone
``serve_bench --assert`` run per the bench-box discipline)."""

import importlib
import sys

import pytest


@pytest.mark.slow
def test_serve_bench_short():
    sys.path.insert(0, "scripts")
    try:
        bench = importlib.import_module("serve_bench")
    finally:
        sys.path.pop(0)
    summary = bench.run(seconds=4.0, readers=2, batch=32)
    bad = bench.check(summary, min_reads_per_s=500.0,
                      min_hit_ratio=0.5, min_replica_share=0.5,
                      max_p999_ms=2000.0,
                      min_result_hit_ratio=0.5,
                      min_index_speedup=1.0)
    assert bad == [], (bad, summary)
    assert summary["rounds_committed"] >= 1
    assert summary["stale_rows"] == 0
    assert summary["index_identical"]
