"""Introspection (ctl/dashboard analog) + troublemaker chaos tests."""

import pytest

from risingwave_tpu.ctl import cluster_info, describe_job
from risingwave_tpu.sql import Engine
from risingwave_tpu.sql.planner import PlannerConfig


def _engine():
    return Engine(PlannerConfig(
        chunk_capacity=128, agg_table_size=512, agg_emit_capacity=128,
        mv_table_size=512, mv_ring_size=1024,
    ))


def test_describe_job_and_cluster_info():
    eng = _engine()
    eng.execute("""
        CREATE SOURCE t (k BIGINT, v BIGINT) WITH (connector='datagen');
        CREATE MATERIALIZED VIEW m AS
        SELECT k % 8 AS g, count(*) AS n FROM t GROUP BY k % 8;
    """)
    eng.tick(barriers=2, chunks_per_barrier=1)
    info = describe_job(eng.jobs[0])
    assert info["name"] == "m"
    assert info["committed_epoch"] > 0
    execs = {e["executor"]: e for e in info["executors"]}
    agg = next(v for k, v in execs.items() if "HashAgg" in k)
    assert agg["groups"] == 8
    assert agg["overflow"] == 0 and agg["inconsistency"] == 0
    mv = next(v for k, v in execs.items() if "Materialize" in k)
    assert mv["groups"] == 8

    ci = cluster_info(eng)
    assert any(c["name"] == "m" and c["kind"] == "mview"
               for c in ci["catalog"])
    assert ci["system_params"]["checkpoint_frequency"] == 1


def test_ctl_cluster_subcommands(tmp_path):
    """``ctl cluster {workers,jobs,epochs}`` against a RUNNING meta
    (online RPC, mirroring the offline ``ctl storage`` pattern)."""
    from risingwave_tpu.cluster import ComputeWorker, MetaService
    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.ctl import (
        cluster_epochs,
        cluster_faults,
        cluster_jobs,
        cluster_workers,
    )

    cfg = RwConfig.from_dict({
        "streaming": {"chunk_size": 64},
        "state": {"agg_table_size": 256, "agg_emit_capacity": 64,
                  "mv_table_size": 256, "mv_ring_size": 512},
    })
    meta = MetaService(str(tmp_path), heartbeat_timeout_s=5.0)
    meta.start(port=0, monitor=False)
    addr = f"127.0.0.1:{meta.rpc_port}"
    w = ComputeWorker(addr, str(tmp_path), config=cfg,
                      heartbeat_interval_s=0.5).start()
    try:
        meta.execute_ddl(
            "CREATE SOURCE t (k BIGINT) WITH (connector='datagen');"
            "CREATE MATERIALIZED VIEW cv AS "
            "SELECT k % 2 AS b, count(*) AS n FROM t GROUP BY k % 2"
        )
        assert meta.tick(1)["committed"]

        workers = cluster_workers(addr)
        assert len(workers) == 1
        assert workers[0]["alive"] is True
        assert workers[0]["jobs"] == ["cv"]
        assert workers[0]["heartbeat_age_s"] >= 0.0

        jobs = cluster_jobs(addr)
        assert jobs == [{
            "name": "cv", "mvs": ["cv"],
            "worker": w.worker_id, "rounds": 1,
            "pinned_epoch": jobs[0]["pinned_epoch"],
            "committed_epoch": jobs[0]["committed_epoch"],
            "sealed_epoch": jobs[0]["sealed_epoch"],
            "durable_epoch": jobs[0]["durable_epoch"],
            "partitions": None,
        }]
        assert jobs[0]["pinned_epoch"] > 0
        assert jobs[0]["pinned_epoch"] == jobs[0]["committed_epoch"]
        # a committed round implies every upload acked: seal == durable
        assert jobs[0]["durable_epoch"] == jobs[0]["sealed_epoch"]

        ep = cluster_epochs(addr)
        assert ep["cluster_epoch"] == 1
        assert ep["manifest_epoch"] == jobs[0]["pinned_epoch"]
        assert ep["failovers"] == 0
        assert ep["jobs"]["cv"]["rounds"] == 1
        # the async-checkpoint split is visible in the ctl surface
        assert ep["jobs"]["cv"]["sealed_epoch"] > 0
        assert ep["jobs"]["cv"]["upload_lag_epochs"] == 0

        # ``ctl cluster faults``: the chaos observability surface —
        # injected/retried/gave-up counters per node (no fabric armed
        # here, so everything reads zero/None but the SHAPE is live)
        fl = cluster_faults(addr)
        assert fl["meta"]["fabric"] is None
        assert fl["meta"]["rpc_retries_total"] == 0
        assert fl["meta"]["rpc_retry_gave_up_total"] == 0
        wf = fl["workers"][str(w.worker_id)] \
            if str(w.worker_id) in fl["workers"] \
            else fl["workers"][w.worker_id]
        assert wf["registrations"] == 1
        assert wf["checkpoint_upload_retries_total"] == 0
    finally:
        w.stop()
        meta.stop()


def test_ctl_cluster_metrics_and_trace(tmp_path):
    """``ctl cluster metrics`` (one aggregated labeled scrape) and
    ``ctl cluster trace --chrome`` (one cross-role round tree) against
    a RUNNING meta, via the same online-RPC helpers the CLI calls."""
    import json

    from risingwave_tpu.cluster import ComputeWorker, MetaService
    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.common.trace import GLOBAL_TRACE
    from risingwave_tpu.ctl import cluster_metrics, cluster_trace

    cfg = RwConfig.from_dict({
        "streaming": {"chunk_size": 64},
        "state": {"agg_table_size": 256, "agg_emit_capacity": 64,
                  "mv_table_size": 256, "mv_ring_size": 512},
    })
    role, n = GLOBAL_TRACE.role, GLOBAL_TRACE.sample_n
    GLOBAL_TRACE.configure(role="proc", sample_n=1)
    GLOBAL_TRACE.clear()
    meta = MetaService(str(tmp_path), heartbeat_timeout_s=5.0)
    meta.start(port=0, monitor=False)
    addr = f"127.0.0.1:{meta.rpc_port}"
    w = ComputeWorker(addr, str(tmp_path), config=cfg,
                      heartbeat_interval_s=0.5).start()
    try:
        meta.execute_ddl(
            "CREATE SOURCE t (k BIGINT) WITH (connector='datagen');"
            "CREATE MATERIALIZED VIEW cv AS "
            "SELECT k % 2 AS b, count(*) AS n FROM t GROUP BY k % 2"
        )
        assert meta.tick(1)["committed"]

        text = cluster_metrics(addr)
        assert 'role="meta"' in text
        assert 'barrier_phase_seconds_bucket{job="cv"' in text
        assert text.count("# TYPE cluster_epoch_committed gauge") == 1

        chrome = tmp_path / "round1.json"
        tr = cluster_trace(addr, round=1, chrome=str(chrome))
        assert tr["round"] == 1 and tr["check"]["complete"]
        names = set(tr["check"]["names"])
        assert {"round", "barrier", "commit", "seal"} <= names
        ct = json.loads(chrome.read_text())
        assert any(e.get("ph") == "X" for e in ct["traceEvents"])
    finally:
        GLOBAL_TRACE.configure(role=role, sample_n=n)
        GLOBAL_TRACE.clear()
        w.stop()
        meta.stop()


def test_ctl_pushdown_online_and_offline_agree(tmp_path, capsys):
    """ISSUE 18 satellite: ``ctl cluster pushdown <meta>`` (online)
    and ``ctl storage policy <dir>`` (offline, over the cold data_dir)
    report the SAME manifest-carried expiry-policy doc — a live
    compactor and an offline ``ctl storage compact`` can never
    disagree on a horizon."""
    import json

    from risingwave_tpu.cluster import ComputeWorker, MetaService
    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.ctl import _storage_main, cluster_pushdown

    cfg = RwConfig.from_dict({
        "streaming": {"chunk_size": 64},
        "state": {"agg_table_size": 256, "agg_emit_capacity": 64,
                  "mv_table_size": 256, "mv_ring_size": 512},
    })
    meta = MetaService(str(tmp_path), heartbeat_timeout_s=5.0)
    meta.start(port=0, monitor=False)
    addr = f"127.0.0.1:{meta.rpc_port}"
    w = ComputeWorker(addr, str(tmp_path), config=cfg,
                      heartbeat_interval_s=0.5).start()
    try:
        meta.execute_ddl(
            "CREATE SOURCE t (k BIGINT) WITH (connector='datagen');"
            "CREATE MATERIALIZED VIEW cv WITH (ttl = '1') AS "
            "SELECT k % 2 AS b, count(*) AS n FROM t GROUP BY k % 2"
        )
        assert meta.tick(2)["committed"]

        pd = cluster_pushdown(addr)
        assert pd["version_id"] >= 1
        pol = pd["pushdown"]["policies"]["cv"]
        # the worker derived horizon = max(b) - ttl = 1 - 1 at export;
        # the meta folded the doc into the round's manifest delta
        assert pol["mode"] == "ttl"
        assert pol["column"] == "b" and pol["ttl"] == 1
        assert pol["horizon"] == 0
        assert pd["pushdown"]["rows_elided"] >= 0
        assert pd["serving"] == {}  # no replicas registered here
    finally:
        w.stop()
        meta.stop()

    # OFFLINE round-trip: the policy rides the manifest, so the CLI
    # over the stopped cluster's data_dir prints the identical doc
    _storage_main(["policy", str(tmp_path)])
    off = json.loads(capsys.readouterr().out)
    assert off["policies"]["cv"] == pol
    assert off["version_id"] >= pd["version_id"]


def test_troublemaker_corruption_is_caught():
    """Injected op corruption must surface via consistency counters,
    never silently wrong results (ref RW_UNSAFE_ENABLE_INSANE_MODE)."""
    from risingwave_tpu.expr.agg import AggCall
    from risingwave_tpu.expr.node import col
    from risingwave_tpu.stream.fragment import Fragment
    from risingwave_tpu.stream.hash_join import HashJoinExecutor
    from risingwave_tpu.stream.troublemaker import TroublemakerExecutor
    from risingwave_tpu.common.chunk import Chunk
    from risingwave_tpu.common.types import DataType, Schema
    import numpy as np

    schema = Schema.of(("k", DataType.INT64), ("v", DataType.INT64))
    tm = TroublemakerExecutor(schema, seed=7, ratio=4)
    frag = Fragment([tm])
    st = frag.init_states()
    arrays = [np.arange(64, dtype=np.int64),
              np.arange(64, dtype=np.int64)]
    st, out = frag.step(st, Chunk.from_numpy(schema, arrays))
    ops = [r[0] for r in out.to_rows()]
    assert ops.count(1) > 0  # some inserts flipped to deletes

    # the corrupted stream hits a join side: deletes of never-inserted
    # rows must be COUNTED as inconsistencies
    join = HashJoinExecutor(
        schema, schema, [col("k")], [col("k")],
        table_size=256, bucket_cap=4, out_capacity=256,
    )
    jst = join.init_state()
    jst, _ = join.apply(jst, out, "left")
    assert int(jst.left.inconsistency) > 0


def test_ctl_storage_scrub_offline_finds_planted_bit_flip(tmp_path):
    """Integrity satellite: ``ctl storage scrub <dir>`` verifies every
    SST, the version log chain, and every checkpoint object OFFLINE —
    a planted bit-flip is reported, a clean dir passes."""
    import os

    import numpy as np

    from risingwave_tpu.ctl import storage_scrub
    from risingwave_tpu.storage.checkpoint_store import CheckpointStore
    from risingwave_tpu.storage.hummock import (
        HummockStorage,
        LocalFsObjectStore,
    )

    data_dir = str(tmp_path)
    storage = HummockStorage(
        LocalFsObjectStore(os.path.join(data_dir, "hummock")))
    keys = [f"k{i:04d}".encode() for i in range(150)]
    storage.write_batch([(k, b"v" + k) for k in keys], epoch=1)
    ck = CheckpointStore(data_dir, keep_epochs=8)
    ck.save("job", 1, {"a": np.arange(64, dtype=np.int64)},
            {"offset": 1})

    clean = storage_scrub(data_dir)
    assert clean["ok"] is True
    assert clean["ssts_verified"] == 1
    assert clean["checkpoints_verified"] == 2  # npz + meta
    assert clean["corrupt"] == []

    # plant one bit flip in the SST and one in the checkpoint object
    sst_key = next(iter(storage.versions.current.all_keys()))
    with open(os.path.join(data_dir, "hummock", sst_key),
              "r+b") as f:
        f.seek(20)
        b = f.read(1)
        f.seek(20)
        f.write(bytes([b[0] ^ 2]))
    with open(os.path.join(data_dir, "job", "epoch_1.npz"),
              "r+b") as f:
        f.seek(12)
        f.write(b"\x3c")

    dirty = storage_scrub(data_dir)
    assert dirty["ok"] is False
    kinds = sorted(k for k, _ in dirty["corrupt"])
    assert kinds == ["checkpoint", "sst"]
    assert ("sst", sst_key) in dirty["corrupt"]
    assert ("checkpoint", "job/epoch_1.npz") in dirty["corrupt"]
