"""Device hash-table tests (the state backbone of agg/join/mview)."""

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.state.hash_table import HashTable


def _i64(vals):
    return jnp.asarray(np.asarray(vals, np.int64))


def _valid(n, cap=None):
    cap = cap or n
    v = np.zeros(cap, np.bool_)
    v[:n] = True
    return jnp.asarray(v)


def test_insert_and_lookup():
    t = HashTable.create([jnp.zeros((1,), jnp.int64)], 64)
    keys = [_i64([10, 20, 30, 10])]
    t, slots, inserted, overflow = t.lookup_or_insert(keys, _valid(4))
    s = np.asarray(slots)
    assert not np.asarray(overflow).any()
    # duplicate key resolves to the same slot
    assert s[0] == s[3]
    assert len({s[0], s[1], s[2]}) == 3
    # exactly one insert for the duplicated key
    assert np.asarray(inserted).sum() == 3
    assert int(t.count()) == 3

    slots2, found = t.lookup([_i64([20, 99, 10, 0])], _valid(3, 4))
    f = np.asarray(found)
    assert list(f) == [True, False, True, False]
    assert np.asarray(slots2)[0] == s[1]


def test_collision_heavy_small_table():
    # 16 slots, 12 keys — forced probing
    t = HashTable.create([jnp.zeros((1,), jnp.int64)], 16)
    keys = np.arange(12, dtype=np.int64) * 1000
    t, slots, _, overflow = t.lookup_or_insert([_i64(keys)], _valid(12))
    assert not np.asarray(overflow).any()
    assert int(t.count()) == 12
    # every key findable, distinct slots
    slots2, found = t.lookup([_i64(keys)], _valid(12))
    assert np.asarray(found).all()
    assert len(set(np.asarray(slots2).tolist())) == 12
    assert (np.asarray(slots2) == np.asarray(slots)).all()


def test_overflow_reported():
    t = HashTable.create([jnp.zeros((1,), jnp.int64)], 4)
    keys = np.arange(8, dtype=np.int64)
    t, _, _, overflow = t.lookup_or_insert([_i64(keys)], _valid(8))
    assert np.asarray(overflow).sum() == 4
    assert int(t.count()) == 4


def test_tombstone_preserves_probe_chain():
    t = HashTable.create([jnp.zeros((1,), jnp.int64)], 8)
    # insert keys until some collide, then delete an early chain member
    keys = np.asarray([1, 9, 17, 25], np.int64)  # likely same bucket mod 8
    t, slots, _, _ = t.lookup_or_insert([_i64(keys)], _valid(4))
    s = np.asarray(slots)
    # delete the first key's slot
    t = t.clear_slots(jnp.asarray([s[0]], jnp.int32), jnp.asarray([True]))
    # the rest must still be findable (chain not broken)
    slots2, found = t.lookup([_i64(keys)], _valid(4))
    f = np.asarray(found)
    assert list(f) == [False, True, True, True]
    # re-insert the deleted key: must not duplicate others
    t, slots3, ins, _ = t.lookup_or_insert([_i64([1])], _valid(1))
    assert np.asarray(ins)[0]
    slots4, found4 = t.lookup([_i64(keys)], _valid(4))
    assert np.asarray(found4).all()


def test_multi_column_and_string_keys():
    from risingwave_tpu.common.chunk import encode_strings, StrCol

    data, lens = encode_strings(["abc", "abd", "abc"], 8)
    scol = StrCol(jnp.asarray(data), jnp.asarray(lens))
    icol = _i64([1, 1, 1])
    t = HashTable.create(
        [jnp.zeros((1,), jnp.int64),
         StrCol(jnp.zeros((1, 8), jnp.uint8), jnp.zeros((1,), jnp.int32))],
        32,
    )
    t, slots, _, _ = t.lookup_or_insert([icol, scol], _valid(3))
    s = np.asarray(slots)
    assert s[0] == s[2] and s[0] != s[1]


def test_rehash_reclaims_tombstones():
    t = HashTable.create([jnp.zeros((1,), jnp.int64)], 16)
    keys = np.arange(10, dtype=np.int64)
    t, slots, _, _ = t.lookup_or_insert([_i64(keys)], _valid(10))
    t = t.clear_slots(slots, jnp.asarray([True] * 5 + [False] * 5))
    assert int(t.tombstone_count()) == 5
    fresh, moved = t.rehashed()
    assert int(fresh.tombstone_count()) == 0
    assert int(fresh.count()) == 5
    slots2, found = fresh.lookup([_i64(keys)], _valid(10))
    assert list(np.asarray(found)) == [False] * 5 + [True] * 5
