"""Storage layer tests: native codec, SST format, durable checkpoints."""

import os
import zlib

import numpy as np
import pytest

from risingwave_tpu.storage import codec
from risingwave_tpu.storage.sst import (
    TOMBSTONE,
    SstReader,
    merge_scan,
    write_sst,
)
from risingwave_tpu.storage.checkpoint_store import CheckpointStore


def test_native_codec_builds():
    # the C++ library should build in this image (g++ present)
    assert codec.native_available()


def test_memcomparable_i64_order_and_roundtrip():
    vals = np.asarray(
        [-(2**63), -55, -1, 0, 1, 7, 2**62, 2**63 - 1], np.int64
    )
    enc = codec.mc_encode_i64(vals)
    assert [bytes(e) for e in enc] == sorted(bytes(e) for e in enc)
    np.testing.assert_array_equal(codec.mc_decode_i64(enc), vals)


def test_memcomparable_f64_order_and_roundtrip():
    vals = np.asarray(
        [-np.inf, -1e300, -1.5, -0.0, 0.0, 1e-300, 2.5, np.inf], np.float64
    )
    enc = codec.mc_encode_f64(vals)
    b = [bytes(e) for e in enc]
    assert b == sorted(b)
    dec = codec.mc_decode_f64(enc)
    # -0.0 encodes as +0.0 ordering-wise; compare with ==
    np.testing.assert_array_equal(dec, vals)


def test_block_roundtrip():
    keys = [f"key{i:04d}".encode() for i in range(100)]
    vals = [f"value-{i}".encode() * (i % 5 + 1) for i in range(100)]
    ko = np.cumsum([0] + [len(k) for k in keys]).astype(np.int64)
    vo = np.cumsum([0] + [len(v) for v in vals]).astype(np.int64)
    blk = codec.block_encode(
        np.frombuffer(b"".join(keys), np.uint8), ko,
        np.frombuffer(b"".join(vals), np.uint8), vo,
    )
    k2, ko2, v2, vo2 = codec.block_decode(blk)
    kb, vb = k2.tobytes(), v2.tobytes()
    got = [
        (kb[ko2[i]:ko2[i + 1]], vb[vo2[i]:vo2[i + 1]])
        for i in range(len(ko2) - 1)
    ]
    assert got == list(zip(keys, vals))


def test_sst_write_read_scan(tmp_path):
    n = 5000
    keys = [f"{i:08d}".encode() for i in range(n)]
    vals = [f"v{i}".encode() for i in range(n)]
    path = str(tmp_path / "t.sst")
    meta = write_sst(path, keys, vals, block_bytes=1024)
    assert meta.n_records == n
    r = SstReader(path)
    assert r.n_records == n
    assert r.get(b"00000042") == b"v42"
    assert r.get(b"99999999") is None
    got = list(r.scan(b"00001000", b"00001010"))
    assert [k for k, _ in got] == keys[1000:1010]


def test_sst_merge_scan_newest_wins(tmp_path):
    old = str(tmp_path / "old.sst")
    new = str(tmp_path / "new.sst")
    write_sst(old, [b"a", b"b", b"c"], [b"1", b"2", b"3"])
    write_sst(new, [b"b", b"c", b"d"], [b"20", TOMBSTONE, b"40"])
    got = list(merge_scan([SstReader(new), SstReader(old)]))
    assert got == [(b"a", b"1"), (b"b", b"20"), (b"d", b"40")]


def test_checkpoint_store_survives_restart(tmp_path):
    """Job persists checkpoints; a FRESH job object recovers from disk."""
    from risingwave_tpu.common.chunk import Chunk
    from risingwave_tpu.common.types import DataType, Schema
    from risingwave_tpu.expr.agg import count_star
    from risingwave_tpu.expr.node import col
    from risingwave_tpu.stream.fragment import Fragment
    from risingwave_tpu.stream.hash_agg import HashAggExecutor
    from risingwave_tpu.stream.materialize import MaterializeExecutor
    from risingwave_tpu.stream.runtime import StreamingJob

    schema = Schema.of(("g", DataType.INT64), ("v", DataType.INT64))

    class Src:
        def __init__(self):
            self.offset = 0

        def next_chunk(self):
            ar = [np.arange(4, dtype=np.int64) % 2,
                  np.full(4, self.offset, np.int64)]
            self.offset += 1
            return Chunk.from_numpy(schema, ar)

        def state(self):
            return {"offset": self.offset}

    def build():
        agg = HashAggExecutor(
            schema, [("g", col("g"))], [count_star("n")],
            table_size=64, emit_capacity=16,
        )
        mv = MaterializeExecutor(agg.out_schema, [0], table_size=64)
        return Fragment([agg, mv]), mv

    store = CheckpointStore(str(tmp_path / "ckpt"))
    frag, mv = build()
    job = StreamingJob(Src(), frag, "j1", checkpoint_store=store)
    job.run(barriers=3, chunks_per_barrier=1)
    want = sorted(mv.to_host(job.states[1]))
    committed = job.committed_epoch
    assert store.committed_epoch("j1") == committed

    # "process restart": fresh objects, recover from disk
    frag2, mv2 = build()
    job2 = StreamingJob(Src(), frag2, "j1", checkpoint_store=store)
    job2.recover()
    assert job2.committed_epoch == committed
    assert job2.source.offset == 3
    assert sorted(mv2.to_host(job2.states[1])) == want
    # and it keeps running correctly
    job2.run(barriers=1, chunks_per_barrier=1)
    assert sorted(mv2.to_host(job2.states[1])) == [(0, 8), (1, 8)]


def test_checkpoint_store_gc(tmp_path):
    # full_interval=1: every epoch is a full snapshot, so GC can drop
    # old epochs immediately
    store = CheckpointStore(str(tmp_path), keep_epochs=2, full_interval=1)
    states = {"x": np.arange(5)}
    for e in (10, 20, 30):
        store.save("j", e, states, {})
    files = os.listdir(str(tmp_path / "j"))
    assert "epoch_10.npz" not in files
    assert "epoch_30.npz" in files
    assert store.committed_epoch("j") == 30


def test_incremental_checkpoint_bytes_scale_with_activity(tmp_path):
    """Delta checkpoints persist only dirty blocks (ref uploader
    per-epoch deltas); restore replays full + chain."""
    store = CheckpointStore(str(tmp_path), keep_epochs=8,
                            full_interval=16, block_elems=1 << 10)
    big = np.zeros(1 << 16, np.int64)  # 64 blocks
    states = {"big": big, "ctr": np.zeros((), np.int64)}
    store.save("j", 1, states, {"off": 1})
    assert store.checkpoint_kind("j", 1) == "full"
    full_bytes = store.checkpoint_bytes("j", 1)

    # touch one block + the scalar -> tiny delta
    big2 = big.copy()
    big2[5] = 99
    store.save("j", 2, {"big": big2, "ctr": np.int64(1)}, {"off": 2})
    assert store.checkpoint_kind("j", 2) == "delta"
    delta_bytes = store.checkpoint_bytes("j", 2)
    assert delta_bytes < full_bytes // 8

    # untouched epoch -> near-empty delta
    store.save("j", 3, {"big": big2, "ctr": np.int64(1)}, {"off": 3})
    assert store.checkpoint_bytes("j", 3) < delta_bytes

    # restore target epoch reconstructs through the chain
    epoch, loaded, src = store.load("j", 3)
    assert epoch == 3 and src == {"off": 3}
    assert loaded["big"][5] == 99 and int(loaded["ctr"]) == 1
    assert (loaded["big"] == big2).all()
    # time travel to the mid-chain epoch
    _, loaded2, src2 = store.load("j", 2)
    assert src2 == {"off": 2} and loaded2["big"][5] == 99


def test_incremental_checkpoint_gc_keeps_chain_base(tmp_path):
    store = CheckpointStore(str(tmp_path), keep_epochs=2,
                            full_interval=4, block_elems=1 << 10)
    arr = np.zeros(1 << 12, np.int64)
    for e in range(1, 7):
        arr = arr.copy()
        arr[e] = e
        store.save("j", e, {"a": arr}, {})
    # latest epochs stay loadable even though their base full is older
    # than keep_epochs
    epoch, loaded, _ = store.load("j")
    assert epoch == 6 and loaded["a"][6] == 6 and loaded["a"][3] == 3


def test_export_mv_sst(tmp_path):
    from risingwave_tpu.common.chunk import Chunk
    from risingwave_tpu.common.types import DataType, Schema
    from risingwave_tpu.stream.fragment import Fragment
    from risingwave_tpu.stream.materialize import MaterializeExecutor

    schema = Schema.of(("k", DataType.INT64), ("v", DataType.INT64))
    mv = MaterializeExecutor(schema, [0], table_size=64)
    frag = Fragment([mv])
    st = frag.init_states()
    st, _ = frag.step(st, Chunk.from_pretty("""
        I I
        + 3 30
        + 1 10
        + 2 20
    """, names=["k", "v"]))
    store = CheckpointStore(str(tmp_path))
    path = store.export_mv_sst("j", 1, mv, st[0])
    r = SstReader(path)
    import pickle
    rows = [pickle.loads(v) for _, v in r.scan()]
    assert rows == [(1, 10), (2, 20), (3, 30)]  # pk-ordered


def test_engine_free_mv_read_from_sst(tmp_path):
    """Serving an MV from its exported SST without the engine/device
    state — the batch-scan-from-storage pattern (SURVEY §3.4)."""
    import pickle

    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig
    from risingwave_tpu.storage.sst import SstReader

    eng = Engine(PlannerConfig(
        chunk_capacity=64, agg_table_size=256, agg_emit_capacity=64,
        mv_table_size=256, mv_ring_size=1024,
    ), data_dir=str(tmp_path))
    eng.execute("""
        CREATE SOURCE t (k BIGINT, v BIGINT) WITH (connector='datagen');
        CREATE MATERIALIZED VIEW m AS
        SELECT k % 4 AS g, count(*) AS n FROM t GROUP BY k % 4;
    """)
    eng.tick(barriers=2, chunks_per_barrier=1)
    entry = eng.catalog.get("m")
    live = sorted(eng.execute("SELECT g, n FROM m"))

    job = entry.job
    path = eng.checkpoint_store.export_mv_sst(
        "m", job.committed_epoch, entry.mv_executor,
        job.states[entry.mv_state_index[0]],
    )
    # a "different process": plain SST scan, no engine objects
    rows = sorted(
        (int(r[0]), int(r[1]))
        for _, v in SstReader(path).scan()
        for r in [pickle.loads(v)]
    )
    assert rows == [(int(a), int(b)) for a, b in live]


def test_engine_soak_checkpoint_bytes_stay_incremental(tmp_path):
    """A running windowed job's steady-state checkpoints are deltas
    whose bytes track epoch activity, not state size (verdict r3 ask:
    snapshot cadence can stay at 1 without full-state uploads)."""
    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig

    eng = Engine(PlannerConfig(
        chunk_capacity=256, agg_table_size=1 << 12,
        agg_emit_capacity=256, mv_table_size=1 << 13,
        mv_ring_size=1 << 14,
    ), data_dir=str(tmp_path))
    eng.execute(
        "CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
        " channel VARCHAR, url VARCHAR, date_time TIMESTAMP,"
        " WATERMARK FOR date_time AS date_time)"
        " WITH (connector='nexmark', nexmark.table='bid',"
        " nexmark.event.rate='1000');"
        "CREATE MATERIALIZED VIEW w AS SELECT window_start,"
        " count(*) AS n FROM TUMBLE(bid, date_time,"
        " INTERVAL '1' SECOND) GROUP BY window_start;"
    )
    store = eng.checkpoint_store
    eng.tick(barriers=12, chunks_per_barrier=1)
    job = eng.jobs[0].name
    epochs = store.epochs(job)
    assert len(epochs) >= 2
    kinds = [store.checkpoint_kind(job, e) for e in epochs]
    sizes = {k: store.checkpoint_bytes(job, e)
             for e, k in zip(epochs, kinds)}
    assert "delta" in kinds, kinds
    # the steady-state deltas are a small fraction of a full snapshot
    full_size = max(store.checkpoint_bytes(job, e)
                    for e, k in zip(epochs, kinds) if k == "full") \
        if "full" in kinds else None
    delta_sizes = [store.checkpoint_bytes(job, e)
                   for e, k in zip(epochs, kinds) if k == "delta"]
    if full_size is not None and delta_sizes:
        assert min(delta_sizes) < full_size // 4, (sizes, kinds)
    # and recovery from the chain still works (cold-start bootstrap
    # replays the DDL log and restores the delta chain)
    eng2 = Engine(PlannerConfig(
        chunk_capacity=256, agg_table_size=1 << 12,
        agg_emit_capacity=256, mv_table_size=1 << 13,
        mv_ring_size=1 << 14,
    ), data_dir=str(tmp_path))
    a = sorted(map(tuple, eng.execute("SELECT * FROM w")))
    b = sorted(map(tuple, eng2.execute("SELECT * FROM w")))
    assert a == b
