"""Slow wrapper around ``scripts/profile_q8.py --assert``: the q8
join-path regression gate (probe counts, fused dispatch, probe-effort
and drain-window budgets) as a pytest target.

Run with: ``pytest -m slow tests/test_profile_q8_assert.py``
"""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_profile_q8_assert_small():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "profile_q8.py"),
         "--assert", "--small"],
        env=env, capture_output=True, text=True, timeout=1800, cwd=ROOT,
    )
    assert out.returncode == 0, (
        f"profile_q8 --assert failed:\n{out.stdout}\n{out.stderr[-2000:]}"
    )
    assert "profile_q8 --assert: OK" in out.stdout
