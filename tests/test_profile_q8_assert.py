"""Slow wrapper around ``scripts/profile_q8.py --assert``: the q8
join-path regression gate (probe counts, fused dispatch, probe-effort
and drain-window budgets) as a pytest target.

Run with: ``pytest -m slow tests/test_profile_q8_assert.py``
"""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_profile_q8_assert_small():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "profile_q8.py"),
         "--assert", "--small"],
        env=env, capture_output=True, text=True, timeout=1800, cwd=ROOT,
    )
    assert out.returncode == 0, (
        f"profile_q8 --assert failed:\n{out.stdout}\n{out.stderr[-2000:]}"
    )
    assert "profile_q8 --assert: OK" in out.stdout


@pytest.mark.slow
def test_profile_q8_assert_sharded():
    """ISSUE 9: the sharded q8 gate — one fused shard_map dispatch per
    barrier window on 8 host-emulated devices, zero per-chunk host
    dispatches, bounded exchange traffic, per-shard delta snapshots."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "profile_q8.py"),
         "--assert", "--sharded"],
        env=env, capture_output=True, text=True, timeout=1800, cwd=ROOT,
    )
    assert out.returncode == 0, (
        f"profile_q8 --assert --sharded failed:\n{out.stdout}\n"
        f"{out.stderr[-2000:]}"
    )
    assert "profile_q8 --assert --sharded: OK" in out.stdout
