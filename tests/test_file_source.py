"""External file-tailing JSON source: e2e ingest, tailing, recovery.

Ref: SplitEnumerator/SplitReader (src/connector/src/source/base.rs),
parser chunk builder (src/connector/src/parser/chunk_builder.rs) —
offsets ride checkpoints, recovery replays from the committed cursor.
"""

import json
import os

import pytest

from risingwave_tpu.sql import Engine
from risingwave_tpu.sql.planner import PlannerConfig


def small_engine(data_dir=None) -> Engine:
    return Engine(PlannerConfig(
        chunk_capacity=64,
        agg_table_size=1 << 9, agg_emit_capacity=1 << 8,
        mv_table_size=1 << 9, mv_ring_size=1 << 10,
        topn_pool_size=1 << 8, topn_emit_capacity=1 << 7,
    ), data_dir=data_dir)


def write_lines(path, rows, mode="a"):
    with open(path, mode) as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


DDL = ("CREATE SOURCE ev (k BIGINT, v BIGINT, s VARCHAR, "
       "ts TIMESTAMP) WITH (connector='filetail', path='{path}')")


def test_filetail_e2e_and_tailing(tmp_path):
    path = str(tmp_path / "events.jsonl")
    write_lines(path, [
        {"k": 1, "v": 10, "s": "a", "ts": "2015-07-15 00:00:01"},
        {"k": 2, "v": 20, "s": "b", "ts": "2015-07-15 00:00:02"},
    ], mode="w")
    eng = small_engine()
    eng.execute(DDL.format(path=path))
    eng.execute("CREATE MATERIALIZED VIEW mv AS "
                "SELECT k, sum(v) AS s FROM ev GROUP BY k")
    eng.tick(barriers=2)
    assert sorted(eng.execute("SELECT * FROM mv")) == [(1, 10), (2, 20)]

    # tailing: appended lines appear after later barriers
    write_lines(path, [
        {"k": 1, "v": 5, "s": "c", "ts": "2015-07-15 00:00:03"},
        {"k": 3, "v": 7, "s": "d", "ts": "2015-07-15 00:00:04"},
    ])
    eng.tick(barriers=2)
    assert sorted(eng.execute("SELECT * FROM mv")) == \
        [(1, 15), (2, 20), (3, 7)]


def test_filetail_malformed_rows_counted_not_fatal(tmp_path):
    path = str(tmp_path / "bad.jsonl")
    with open(path, "w") as f:
        f.write('{"k": 1, "v": 1, "s": "x", "ts": "2015-07-15 00:00:01"}\n')
        f.write("this is not json\n")
        f.write('{"k": 2, "v": "NaNope", "s": "y"}\n')   # bad v type
        f.write('{"k": 2, "v": 2, "s": "y", "ts": "2015-07-15 00:00:02"}\n')
    eng = small_engine()
    eng.execute(DDL.format(path=path))
    eng.execute("CREATE MATERIALIZED VIEW mv AS "
                "SELECT k, count(*) AS n FROM ev GROUP BY k")
    eng.tick(barriers=2)
    assert sorted(eng.execute("SELECT * FROM mv")) == [(1, 1), (2, 1)]


def test_filetail_recovery_replays_from_offset(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    data = str(tmp_path / "ckpt")
    write_lines(path, [
        {"k": i % 4, "v": i, "s": f"s{i}",
         "ts": "2015-07-15 00:00:01"} for i in range(40)
    ], mode="w")

    def build(eng):
        eng.execute(DDL.format(path=path))
        eng.execute("CREATE MATERIALIZED VIEW mv AS "
                    "SELECT k, count(*) AS n, sum(v) AS s "
                    "FROM ev GROUP BY k")

    eng = small_engine(data_dir=data)
    build(eng)
    eng.tick(barriers=3)
    want = sorted(map(tuple, eng.execute("SELECT * FROM mv")))
    committed = eng.jobs[0].committed_epoch
    assert committed > 0

    # process restart: cold-start bootstrap + append MORE rows; no
    # duplicates, no loss
    eng2 = small_engine(data_dir=data)
    assert sorted(map(tuple, eng2.execute("SELECT * FROM mv"))) == want
    write_lines(path, [
        {"k": 0, "v": 1000, "s": "zz", "ts": "2015-07-15 00:00:09"}
    ])
    eng2.tick(barriers=3)
    got = {int(r[0]): (int(r[1]), int(r[2]))
           for r in eng2.execute("SELECT * FROM mv")}
    assert got[0] == (11, sum(i for i in range(40) if i % 4 == 0) + 1000)
    assert got[1][0] == 10


def test_filetail_partial_line_not_consumed(tmp_path):
    """A torn write (no trailing newline) must not be parsed early."""
    path = str(tmp_path / "torn.jsonl")
    with open(path, "w") as f:
        f.write('{"k": 1, "v": 1, "s": "x", "ts": "2015-07-15 00:00:01"}\n')
        f.write('{"k": 2, "v": 2, "s"')  # torn
    eng = small_engine()
    eng.execute(DDL.format(path=path))
    eng.execute("CREATE MATERIALIZED VIEW mv AS "
                "SELECT k, count(*) AS n FROM ev GROUP BY k")
    eng.tick(barriers=2)
    assert sorted(eng.execute("SELECT * FROM mv")) == [(1, 1)]
    with open(path, "a") as f:
        f.write(': "y", "ts": "2015-07-15 00:00:02"}\n')  # completed
    eng.tick(barriers=2)
    assert sorted(eng.execute("SELECT * FROM mv")) == [(1, 1), (2, 1)]
