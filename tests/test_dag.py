"""DAG scheduler tests: MV-on-MV cascades, multi-way joins, shared jobs.

Reference counterparts: fragment-graph jobs
(src/frontend/src/stream_fragmenter/mod.rs:388), MV-on-MV via the
materialize fragment's dispatcher (dispatch.rs:62), backfill of the
upstream snapshot (backfill/arrangement_backfill.rs).
"""

import numpy as np
import pytest

from risingwave_tpu.sql import Engine
from risingwave_tpu.sql.planner import PlannerConfig


def small_engine():
    return Engine(PlannerConfig(
        chunk_capacity=64,
        agg_table_size=1 << 10,
        agg_emit_capacity=256,
        join_table_size=1 << 9,
        join_out_capacity=1 << 11,
        mv_table_size=1 << 10,
        mv_ring_size=1 << 12,
        topn_pool_size=256,
        topn_emit_capacity=128,
    ))


BID = """
CREATE SOURCE bid (
    auction BIGINT, bidder BIGINT, price BIGINT, date_time TIMESTAMP
) WITH (connector = 'nexmark', nexmark.table = 'bid',
        nexmark.event.rate = '1000000');
"""


def test_cascade_mv_on_mv():
    """v2 = filter over v1 (project): rows flow through the cascade."""
    eng = small_engine()
    eng.execute(BID)
    eng.execute("""
        CREATE MATERIALIZED VIEW v1 AS
        SELECT auction, price * 2 AS p2 FROM bid;
    """)
    eng.execute("""
        CREATE MATERIALIZED VIEW v2 AS
        SELECT auction, p2 FROM v1 WHERE p2 > 1000;
    """)
    eng.tick(barriers=3, chunks_per_barrier=2)
    v1 = eng.execute("SELECT * FROM v1")
    v2 = eng.execute("SELECT * FROM v2")
    want = sorted(r for r in v1 if r[1] > 1000)
    assert sorted(v2) == want
    assert len(v2) > 0


def test_cascade_backfill_history():
    """An MV created AFTER the upstream has run serves upstream history
    (ref arrangement backfill)."""
    eng = small_engine()
    eng.execute(BID)
    eng.execute("""
        CREATE MATERIALIZED VIEW v1 AS
        SELECT auction, price FROM bid;
    """)
    eng.tick(barriers=3, chunks_per_barrier=2)  # v1 accumulates history
    before = len(eng.execute("SELECT * FROM v1"))
    assert before > 0
    eng.execute("CREATE MATERIALIZED VIEW v2 AS SELECT auction FROM v1;")
    eng.execute("FLUSH")
    v2 = eng.execute("SELECT * FROM v2")
    assert len(v2) >= before  # history backfilled, not started from now


def test_cascade_agg_over_agg():
    """Retractable cascade: agg over an agg MV's changelog."""
    eng = small_engine()
    eng.execute(BID)
    eng.execute("""
        CREATE MATERIALIZED VIEW per_auction AS
        SELECT auction, count(*) AS bids FROM bid GROUP BY auction;
    """)
    eng.execute("""
        CREATE MATERIALIZED VIEW total AS
        SELECT count(*) AS auctions, sum(bids) AS bids
        FROM per_auction;
    """)
    eng.tick(barriers=4, chunks_per_barrier=2)
    per = eng.execute("SELECT * FROM per_auction")
    tot = eng.execute("SELECT * FROM total")
    assert len(tot) == 1
    assert tot[0][0] == len(per)
    assert tot[0][1] == sum(r[1] for r in per)


def test_three_way_join():
    """Nested (left-deep) join tree plans and runs end-to-end."""
    eng = small_engine()
    eng.execute("""
        CREATE TABLE t1 (k BIGINT, a BIGINT);
        CREATE TABLE t2 (k BIGINT, b BIGINT);
        CREATE TABLE t3 (k BIGINT, c BIGINT);
    """)
    eng.execute("""
        CREATE MATERIALIZED VIEW j3 AS
        SELECT t1.a AS a, t2.b AS b, t3.c AS c
        FROM t1 JOIN t2 ON t1.k = t2.k JOIN t3 ON t1.k = t3.k;
    """)
    eng.execute("INSERT INTO t1 VALUES (1, 10), (2, 20), (3, 30)")
    eng.execute("INSERT INTO t2 VALUES (1, 100), (2, 200)")
    eng.execute("INSERT INTO t3 VALUES (1, 1000), (9, 9000)")
    eng.tick(barriers=2, chunks_per_barrier=1)
    rows = eng.execute("SELECT * FROM j3")
    assert sorted(rows) == [(10, 100, 1000)]


def test_join_of_two_mvs_merges_jobs():
    """SELECT from mv JOIN mv: upstream jobs fuse into one DAG."""
    eng = small_engine()
    eng.execute("""
        CREATE TABLE l (k BIGINT, a BIGINT);
        CREATE TABLE r (k BIGINT, b BIGINT);
    """)
    eng.execute(
        "CREATE MATERIALIZED VIEW lv AS SELECT k, a FROM l;"
    )
    eng.execute(
        "CREATE MATERIALIZED VIEW rv AS SELECT k, b * 2 AS b2 FROM r;"
    )
    eng.execute("INSERT INTO l VALUES (1, 10), (2, 20)")
    eng.execute("INSERT INTO r VALUES (2, 200), (3, 300)")
    eng.tick(barriers=2, chunks_per_barrier=1)  # history before the join MV
    eng.execute("""
        CREATE MATERIALIZED VIEW joined AS
        SELECT lv.a AS a, rv.b2 AS b2
        FROM lv JOIN rv ON lv.k = rv.k;
    """)
    eng.execute("FLUSH")
    rows = eng.execute("SELECT * FROM joined")
    assert sorted(rows) == [(20, 400)]  # history joined via backfill
    # live updates keep flowing after the merge
    eng.execute("INSERT INTO l VALUES (3, 30)")
    eng.tick(barriers=2, chunks_per_barrier=1)
    rows = eng.execute("SELECT * FROM joined")
    assert sorted(rows) == [(20, 400), (30, 600)]
    assert len(eng.jobs) == 1  # everything fused into one DAG job


def test_drop_rejects_dependents_then_cascade_drop():
    eng = small_engine()
    eng.execute(BID)
    eng.execute(
        "CREATE MATERIALIZED VIEW v1 AS SELECT auction, price FROM bid;"
    )
    eng.execute("CREATE MATERIALIZED VIEW v2 AS SELECT auction FROM v1;")
    eng.tick(barriers=1, chunks_per_barrier=1)
    with pytest.raises(ValueError):
        eng.execute("DROP MATERIALIZED VIEW v1")
    eng.execute("DROP MATERIALIZED VIEW v2")
    eng.execute("DROP MATERIALIZED VIEW v1")  # now allowed
    eng.tick(barriers=1, chunks_per_barrier=1)
    assert eng.execute("SHOW MATERIALIZED VIEWS") == []


def test_cascade_survives_recovery():
    """Cascaded jobs recover from the shared checkpoint."""
    eng = small_engine()
    eng.execute(BID)
    eng.execute(
        "CREATE MATERIALIZED VIEW v1 AS SELECT auction, price FROM bid;"
    )
    eng.execute("CREATE MATERIALIZED VIEW v2 AS SELECT auction FROM v1;")
    eng.tick(barriers=3, chunks_per_barrier=2)
    v2_committed = eng.execute("SELECT count(*) FROM v2")[0][0]
    # uncommitted progress is rolled back by recovery
    eng.jobs[0].run_chunk(next(iter(eng.jobs[0].sources)))
    eng.recover()
    assert eng.execute("SELECT count(*) FROM v2")[0][0] == v2_committed
    # and the cascade keeps running after recovery
    eng.tick(barriers=2, chunks_per_barrier=2)
    assert eng.execute("SELECT count(*) FROM v2")[0][0] > v2_committed


def test_self_join_of_one_mv_backfills_both_sides():
    """Regression: duplicate taps of one MV must backfill each join
    side exactly once (left first, then right probing the filled left)."""
    eng = small_engine()
    eng.execute("CREATE TABLE t (k BIGINT, v BIGINT);")
    eng.execute("CREATE MATERIALIZED VIEW m AS SELECT k, v FROM t;")
    eng.execute("INSERT INTO t VALUES (1, 10), (1, 11), (2, 20)")
    eng.tick(barriers=2, chunks_per_barrier=1)  # history before the join
    eng.execute("""
        CREATE MATERIALIZED VIEW sj AS
        SELECT a.v AS va, b.v AS vb FROM m a JOIN m b ON a.k = b.k;
    """)
    eng.execute("FLUSH")
    rows = eng.execute("SELECT * FROM sj")
    # snapshot x snapshot: k=1 yields 2x2 pairs, k=2 yields 1
    assert sorted(rows) == [(10, 10), (10, 11), (11, 10), (11, 11),
                            (20, 20)]
    # live rows join against both history and themselves
    eng.execute("INSERT INTO t VALUES (2, 21)")
    eng.tick(barriers=2, chunks_per_barrier=1)
    rows = eng.execute("SELECT * FROM sj")
    assert sorted(r for r in rows if r[0] >= 20) == [
        (20, 20), (20, 21), (21, 20), (21, 21)]


def test_duplicate_create_does_not_mutate_shared_job():
    """Regression: a doomed duplicate CREATE must not attach ghost
    nodes to the running upstream job."""
    eng = small_engine()
    eng.execute("CREATE TABLE t (k BIGINT, v BIGINT);")
    eng.execute("CREATE MATERIALIZED VIEW m AS SELECT k, v FROM t;")
    eng.execute("CREATE MATERIALIZED VIEW m2 AS SELECT k FROM m;")
    n_nodes = len(eng.jobs[0].nodes)
    with pytest.raises(ValueError):
        eng.execute("CREATE MATERIALIZED VIEW m2 AS SELECT v FROM m;")
    assert len(eng.jobs[0].nodes) == n_nodes
    eng.execute(
        "CREATE MATERIALIZED VIEW IF NOT EXISTS m2 AS SELECT v FROM m;"
    )
    assert len(eng.jobs[0].nodes) == n_nodes


def test_drop_detaches_private_sources():
    """Regression: dropping a join MV detaches the source readers it
    added to the shared job."""
    eng = small_engine()
    eng.execute("CREATE TABLE t (k BIGINT, v BIGINT);")
    eng.execute("CREATE TABLE u (k BIGINT, w BIGINT);")
    eng.execute("CREATE MATERIALIZED VIEW m AS SELECT k, v FROM t;")
    eng.execute("""
        CREATE MATERIALIZED VIEW j AS
        SELECT m.v AS v, u.w AS w FROM m JOIN u ON m.k = u.k;
    """)
    job = eng.jobs[0]
    n_sources = len(job.sources)
    eng.execute("DROP MATERIALIZED VIEW j")
    assert len(job.sources) == n_sources - 1
    eng.tick(barriers=2, chunks_per_barrier=1)  # keeps running
    eng.recover()                               # reseeded checkpoint loads
    eng.tick(barriers=1, chunks_per_barrier=1)


def test_retractable_cascade_applies_deletes():
    """Regression: a non-agg cascade over a RETRACTABLE MV must key its
    materialization by the upstream stream key, or every intermediate
    version of a group accumulates."""
    eng = small_engine()
    eng.execute("CREATE TABLE t (k BIGINT, v BIGINT);")
    eng.execute("""
        CREATE MATERIALIZED VIEW counts AS
        SELECT k, count(*) AS n FROM t GROUP BY k;
    """)
    eng.execute("CREATE MATERIALIZED VIEW big AS "
                "SELECT k, n FROM counts WHERE n >= 2;")
    for _ in range(3):
        eng.execute("INSERT INTO t VALUES (1, 0)")
        eng.tick(barriers=1, chunks_per_barrier=1)
    eng.execute("INSERT INTO t VALUES (2, 0)")
    eng.tick(barriers=2, chunks_per_barrier=1)
    # counts: k=1 -> 3, k=2 -> 1; big keeps ONE row for k=1 (latest),
    # not one per intermediate count
    assert sorted(eng.execute("SELECT * FROM counts")) == [(1, 3), (2, 1)]
    assert eng.execute("SELECT * FROM big") == [(1, 3)]
    # SELECT * must not leak the hidden pk bookkeeping columns
    assert all(len(r) == 2 for r in eng.execute("SELECT * FROM big"))
