"""Spill-to-host: state beyond the device table runs to completion.

Ref: the reference treats state larger than memory as the NORM
(state_table.rs:187, managed_lru.rs).  Here rows whose group cannot
claim a device slot divert to a ring and drain into a host (CPU) tier
at snapshot barriers (stream/spill.py); the tier's changelog injects
downstream so the MV sees every group.
"""

import numpy as np

from risingwave_tpu.sql import Engine
from risingwave_tpu.sql.planner import PlannerConfig


def spill_engine(data_dir=None) -> Engine:
    return Engine(PlannerConfig(
        chunk_capacity=128,
        agg_table_size=64,          # 4x fewer slots than live groups
        agg_emit_capacity=256,
        mv_table_size=1 << 10,      # MV must hold every group
        mv_ring_size=1 << 11,
        agg_spill_ring=1 << 10,
    ), data_dir=data_dir)


def _feed(eng, n_keys=256, reps=3):
    rows = []
    for r in range(reps):
        for k in range(n_keys):
            rows.append((k, k * 10 + r))
    # batches keep INSERT statements reasonable
    for i in range(0, len(rows), 64):
        vals = ",".join(f"({k},{v})" for k, v in rows[i:i + 64])
        eng.execute(f"INSERT INTO t VALUES {vals}")
    return rows


def test_agg_spill_4x_key_cardinality():
    eng = spill_engine()
    eng.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    rows = _feed(eng)
    eng.execute(
        "CREATE MATERIALIZED VIEW mv AS "
        "SELECT k, count(*) AS n, sum(v) AS s, max(v) AS mx "
        "FROM t GROUP BY k"
    )
    eng.tick(barriers=6)
    got = {int(r[0]): (int(r[1]), int(r[2]), int(r[3]))
           for r in eng.execute("SELECT * FROM mv")}
    import collections
    want = collections.defaultdict(lambda: [0, 0, -1])
    for k, v in rows:
        want[k][0] += 1
        want[k][1] += v
        want[k][2] = max(want[k][2], v)
    assert len(got) == 256, len(got)
    assert got == {k: tuple(w) for k, w in want.items()}
    # the device table really was too small: the tier absorbed rows
    job = eng.jobs[0]
    tiers = getattr(job, "_spill", [])
    assert tiers and any(t[3].rows_absorbed > 0 for t in tiers)


def test_agg_spill_updates_keep_flowing():
    """Groups owned by the tier keep aggregating on later inserts."""
    eng = spill_engine()
    eng.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    _feed(eng, n_keys=200, reps=1)
    eng.execute(
        "CREATE MATERIALIZED VIEW mv AS SELECT k, count(*) AS n "
        "FROM t GROUP BY k"
    )
    eng.tick(barriers=4)
    n1 = {int(r[0]): int(r[1]) for r in eng.execute("SELECT * FROM mv")}
    assert len(n1) == 200 and all(v == 1 for v in n1.values())
    _feed(eng, n_keys=200, reps=1)
    eng.tick(barriers=4)
    n2 = {int(r[0]): int(r[1]) for r in eng.execute("SELECT * FROM mv")}
    assert len(n2) == 200 and all(v == 2 for v in n2.values()), \
        sorted(set(n2.values()))


def test_agg_spill_recovery(tmp_path):
    """Tier state checkpoints and restores with the job."""
    def build(eng):
        eng.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
        eng.execute(
            "CREATE MATERIALIZED VIEW mv AS SELECT k, count(*) AS n "
            "FROM t GROUP BY k"
        )

    eng = spill_engine(data_dir=str(tmp_path))
    build(eng)
    _feed(eng, n_keys=256, reps=2)
    eng.tick(barriers=4)
    want = sorted(map(tuple, eng.execute("SELECT * FROM mv")))
    assert len(want) == 256

    # cold start: the fresh engine bootstraps catalog + jobs + tier
    # state from data_dir alone (no manual DDL re-execution)
    eng2 = spill_engine(data_dir=str(tmp_path))
    got = sorted(map(tuple, eng2.execute("SELECT * FROM mv")))
    assert got == want


def test_spill_tier_crash_between_saves(tmp_path):
    """Crash INSIDE the commit, after the tier save but before the job
    save (advisor r4 medium): recovery must rewind the tier to the
    nearest tier epoch <= the job's recovered epoch — the stale live
    tier would double-count the replayed rows, a missing tier file
    would silently lose absorbed groups."""
    eng = spill_engine(data_dir=str(tmp_path))
    eng.execute("CREATE TABLE t (k BIGINT, v BIGINT)")
    eng.execute(
        "CREATE MATERIALIZED VIEW mv AS SELECT k, count(*) AS n "
        "FROM t GROUP BY k"
    )
    _feed(eng, n_keys=256, reps=1)
    eng.tick(barriers=4)
    want1 = sorted(map(tuple, eng.execute("SELECT * FROM mv")))
    assert len(want1) == 256

    _feed(eng, n_keys=256, reps=1)
    job = eng.jobs[0]
    store = job.checkpoint_store
    # the job's save now runs in the background uploader as
    # prepare()+commit() (tier saves still go through save() first in
    # the same task) — crash the JOB commit, after the tier save
    real_commit = store.commit

    def crashing_commit(prep):
        if prep["job"] == job.name:
            raise RuntimeError("simulated crash between saves")
        return real_commit(prep)

    store.commit = crashing_commit
    try:
        # the upload fails in the background; the error surfaces on
        # the barrier loop at the tick's drain boundary
        eng.tick(barriers=4)
        raise AssertionError("commit should have crashed")
    except RuntimeError as e:
        assert "upload failed" in str(e) \
            or "simulated crash" in str(e)
    finally:
        store.commit = real_commit

    # recover: the job rewinds to the first commit; the aborted
    # commit's NEWER tier files must be skipped
    eng.recover()
    got = sorted(map(tuple, eng.execute("SELECT * FROM mv")))
    assert got == want1
    # the replayed second batch lands exactly once
    eng.tick(barriers=4)
    n = {int(r[0]): int(r[1]) for r in eng.execute("SELECT * FROM mv")}
    assert len(n) == 256 and all(v == 2 for v in n.values()), \
        sorted(set(n.values()))


def test_dag_agg_spill_over_join():
    """Spill drains for aggregations inside DAG jobs too (join → agg):
    the tier's changelog injects through the node's remaining
    executors and propagates downstream."""
    eng = spill_engine()
    eng.execute("CREATE TABLE item (id BIGINT, grp BIGINT, "
                "PRIMARY KEY (id))")
    eng.execute("CREATE TABLE hit (item BIGINT, w BIGINT)")
    n_groups = 200  # >> agg_table_size(64)
    for i in range(n_groups):
        eng.execute(f"INSERT INTO item VALUES ({i},{i % 7})")
    rows = []
    for i in range(n_groups):
        for r in range(2):
            rows.append((i, 10 * i + r))
    for i in range(0, len(rows), 64):
        vals = ",".join(f"({a},{b})" for a, b in rows[i:i + 64])
        eng.execute(f"INSERT INTO hit VALUES {vals}")
    eng.execute(
        "CREATE MATERIALIZED VIEW mv AS SELECT h.item AS k, "
        "count(*) AS n, sum(h.w) AS s FROM hit h "
        "JOIN item i ON h.item = i.id GROUP BY h.item"
    )
    eng.tick(barriers=6)
    got = {int(r[0]): (int(r[1]), int(r[2]))
           for r in eng.execute("SELECT * FROM mv")}
    want = {i: (2, 10 * i + 10 * i + 1) for i in range(n_groups)}
    assert len(got) == n_groups, len(got)
    assert got == want
    # the tier really absorbed rows (per-shard lists; meshless = 1)
    job = eng.jobs[0]
    tiers = getattr(job, "_spill_tiers", {})
    assert tiers and any(
        t.rows_absorbed for ts in tiers.values() for t in ts
    )
