"""Scale-lite: the elastic vnode scale plane.

- vnode map properties: deterministic across processes, balanced
  within +-1, and N -> N+1 -> N moves only the minimal vnode set;
- the VnodeGateExecutor masks chunks exactly by vnode ownership;
- checkpoint-slice handover: clear + transplant moves exactly the
  sliced vnodes' agg/materialize entries between live states;
- in-process cluster e2e: scale 1 -> 2 -> 1 mid-stream over a
  replicated DML table converges byte-identically to a single node,
  with only moved vnodes transferred;
- meta restart: the scale log re-adopts every partition lineage.
"""

from __future__ import annotations

import json
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.cluster.scale.vnode import (
    initial_map,
    moved_vnodes,
    rebalance,
    vnodes_of_ints,
)

N = 64


# -- vnode map properties ------------------------------------------------
def _balanced(vmap, workers):
    counts = {w: 0 for w in workers}
    for w in vmap:
        counts[w] += 1
    return max(counts.values()) - min(counts.values()) <= 1


def test_vnode_map_balance_and_coverage():
    for workers in ([1], [1, 2], [3, 7, 9], list(range(1, 11))):
        vmap = initial_map(workers, N)
        assert len(vmap) == N
        assert set(vmap) == set(workers)
        assert _balanced(vmap, workers)


def test_rebalance_minimal_movement_out_and_back():
    """Scaling W -> W+1 -> W moves only the minimal vnode set (the new
    worker's quota), touches nothing else, and returns to the exact
    original map."""
    for base in ([1], [1, 2], [1, 2, 3]):
        m0 = initial_map(base, N)
        grown = base + [max(base) + 1]
        m1 = rebalance(m0, grown, N)
        assert _balanced(m1, grown)
        moved = moved_vnodes(m0, m1)
        # every move lands on the NEW worker, exactly its quota
        assert all(dst == grown[-1] for (_, dst) in moved)
        assert sum(len(v) for v in moved.values()) == N // len(grown)
        # unmoved vnodes keep their owner
        for v, w in enumerate(m0):
            if m1[v] != w:
                assert m1[v] == grown[-1]
        m2 = rebalance(m1, base, N)
        assert _balanced(m2, base)
        back = moved_vnodes(m1, m2)
        # scaling back moves ONLY the removed worker's vnodes (no
        # reshuffle among survivors), exactly its quota
        assert all(src == grown[-1] for (src, _) in back)
        assert sum(len(v) for v in back.values()) \
            == sum(1 for w in m1 if w == grown[-1])
        for v, w in enumerate(m1):
            if w != grown[-1]:
                assert m2[v] == w


def test_rebalance_deterministic_across_processes():
    """The map is a pure function of (old, workers): a separate
    interpreter computes the byte-identical map AND the identical
    vnode hashes (no PYTHONHASHSEED anywhere in the path)."""
    m0 = initial_map([1, 2, 3], N)
    m1 = rebalance(m0, [1, 2, 3, 4], N)
    vn = [int(x) for x in np.asarray(
        vnodes_of_ints(np.arange(32, dtype=np.int64), N))]
    prog = (
        "import sys, json; sys.path.insert(0, '.')\n"
        "import numpy as np\n"
        "from risingwave_tpu.cluster.scale.vnode import (\n"
        "    initial_map, rebalance, vnodes_of_ints)\n"
        f"m0 = initial_map([1, 2, 3], {N})\n"
        f"m1 = rebalance(m0, [1, 2, 3, 4], {N})\n"
        "vn = [int(x) for x in np.asarray(\n"
        f"    vnodes_of_ints(np.arange(32, dtype=np.int64), {N}))]\n"
        "print(json.dumps({'m0': m0, 'm1': m1, 'vn': vn}))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", prog], capture_output=True, text=True,
        check=True, env={"PATH": "/usr/bin:/bin", "HOME": "/tmp",
                         "JAX_PLATFORMS": "cpu",
                         "PYTHONHASHSEED": "12345"},
        cwd=".",
    )
    got = json.loads(out.stdout.strip().splitlines()[-1])
    assert got["m0"] == m0
    assert got["m1"] == m1
    assert got["vn"] == vn


def test_rebalance_rejects_empty_and_wrong_size():
    with pytest.raises(ValueError):
        rebalance(None, [], N)
    with pytest.raises(ValueError):
        rebalance([1] * (N - 1), [1], N)


# -- the chunk gate ------------------------------------------------------
def test_vnode_gate_masks_by_ownership():
    from risingwave_tpu.cluster.scale.gate import VnodeGateExecutor
    from risingwave_tpu.common.chunk import Chunk
    from risingwave_tpu.common.types import DataType, Field, Schema
    from risingwave_tpu.expr.node import InputRef

    schema = Schema((Field("k", DataType.INT64, nullable=False),))
    gate = VnodeGateExecutor(schema, InputRef(0), N)
    keys = jnp.arange(100, dtype=jnp.int64)
    chunk = Chunk((keys,), jnp.zeros((100,), jnp.int8),
                  jnp.ones((100,), jnp.bool_), schema)
    vn = np.asarray(vnodes_of_ints(keys, N))
    own = sorted(set(int(v) for v in vn[:7]))  # some owned set
    mask = gate.make_mask(own)
    _, out = gate.apply(mask, chunk)
    got = np.asarray(out.valid)
    want = np.isin(vn, own)
    assert (got == want).all()
    assert 0 < got.sum() < 100  # a strict subset passed
    # full ownership (the init_state default) passes everything
    _, out = gate.apply(gate.init_state(), chunk)
    assert np.asarray(out.valid).all()


# -- checkpoint-slice handover ------------------------------------------
def _agg_pair():
    from risingwave_tpu.common.types import DataType, Field, Schema
    from risingwave_tpu.expr.agg import AggCall
    from risingwave_tpu.expr.node import InputRef
    from risingwave_tpu.stream.hash_agg import HashAggExecutor

    schema = Schema((Field("k", DataType.INT64, nullable=False),
                     Field("v", DataType.INT64, nullable=False)))
    agg = HashAggExecutor(
        schema, [("k", InputRef(0))],
        [AggCall("count", None), AggCall("sum", InputRef(1)),
         AggCall("max", InputRef(1))],
        table_size=1 << 8, emit_capacity=256,
    )
    return schema, agg


def _apply_rows(agg, state, ks, vs):
    from risingwave_tpu.common.chunk import Chunk

    cap = len(ks)
    chunk = Chunk(
        (jnp.asarray(ks, jnp.int64), jnp.asarray(vs, jnp.int64)),
        jnp.zeros((cap,), jnp.int8), jnp.ones((cap,), jnp.bool_),
        agg.in_schema,
    )
    state, _ = agg.apply(state, chunk)
    state, _ = agg.flush(state, jnp.int64(1))
    return state


def _group_rows(agg, state, vnset):
    """Host rows (k, count, sum, max) of groups in a vnode set."""
    occ = np.asarray(state.table.occupied)
    keys = np.asarray(state.table.key_cols[0])
    vn = np.asarray(vnodes_of_ints(keys, N))
    rows = {}
    for slot in np.nonzero(occ)[0]:
        if int(vn[slot]) in vnset:
            rows[int(keys[slot])] = (
                int(np.asarray(state.prims[0])[slot]),
                int(np.asarray(state.prims[1])[slot]),
                int(np.asarray(state.prims[2])[slot]),
                int(np.asarray(state.row_count)[slot]),
            )
    return rows


def test_handover_slice_transplants_only_moved_vnodes():
    from risingwave_tpu.cluster.scale.handover import (
        clear_vnodes,
        slice_partition_states,
        transplant,
    )

    _, agg = _agg_pair()
    donor = _apply_rows(agg, agg.init_state(),
                        list(range(50)), [10 * k for k in range(50)])
    donor = _apply_rows(agg, donor,
                        list(range(25)), [3] * 25)
    keys = np.arange(50, dtype=np.int64)
    vn = np.asarray(vnodes_of_ints(keys, N))
    all_vns = sorted(set(int(v) for v in vn))
    moved = all_vns[: len(all_vns) // 2]
    moved_keys = {int(k) for k, v in zip(keys, vn) if int(v) in moved}

    sl = slice_partition_states([agg], (donor,), moved, N)
    assert sl[0]["n"] == len(moved_keys)  # ONLY moved vnodes' entries

    # recipient holds stale entries for some moved keys — the clear
    # pass must evict them so the transplant refreshes, not resurrects
    recip = _apply_rows(agg, agg.init_state(),
                        [min(moved_keys)], [999999])
    states, cleared = clear_vnodes([agg], (recip,), moved, N)
    assert cleared == 1
    states, n_moved = transplant([agg], states, sl)
    assert n_moved == len(moved_keys)

    assert _group_rows(agg, states[0], set(moved)) == \
        _group_rows(agg, donor, set(moved))
    # nothing outside the moved set leaked across
    assert _group_rows(agg, states[0],
                       set(all_vns) - set(moved)) == {}


def test_handover_refuses_distinct_aggs():
    from risingwave_tpu.cluster.scale.handover import (
        slice_partition_states,
    )
    from risingwave_tpu.common.types import DataType, Field, Schema
    from risingwave_tpu.expr.agg import AggCall
    from risingwave_tpu.expr.node import InputRef
    from risingwave_tpu.stream.hash_agg import HashAggExecutor

    schema = Schema((Field("k", DataType.INT64, nullable=False),
                     Field("v", DataType.INT64, nullable=False)))
    agg = HashAggExecutor(
        schema, [("k", InputRef(0))],
        [AggCall("count", InputRef(1), distinct=True)],
        table_size=1 << 8, emit_capacity=256,
    )
    with pytest.raises(RuntimeError, match="DISTINCT"):
        slice_partition_states([agg], (agg.init_state(),), [0, 1], N)


# -- in-process cluster e2e ---------------------------------------------
CONFIG = {
    "streaming": {"chunk_size": 64},
    "state": {"agg_table_size": 1 << 8, "agg_emit_capacity": 128,
              "mv_table_size": 1 << 8, "mv_ring_size": 1 << 10},
    "storage": {"checkpoint_keep_epochs": 4},
}
DDL = [
    "CREATE TABLE t (k BIGINT, v BIGINT)",
    """CREATE MATERIALIZED VIEW agg AS
       SELECT k, count(*) AS n, sum(v) AS s, max(v) AS mx
       FROM t GROUP BY k""",
]
READ = "SELECT k, n, s, mx FROM agg"


def _mk_cluster(tmp_path, n_workers=2, n_vnodes=16, config=None):
    from risingwave_tpu.cluster import MetaService
    from risingwave_tpu.cluster.worker import ComputeWorker
    from risingwave_tpu.common.config import RwConfig

    cfg = RwConfig.from_dict(config or CONFIG)
    meta = MetaService(str(tmp_path), heartbeat_timeout_s=60.0,
                       scale_partitioning=True, n_vnodes=n_vnodes)
    meta.start(port=0, monitor=False)
    workers = [
        ComputeWorker(f"127.0.0.1:{meta.rpc_port}", str(tmp_path),
                      config=cfg).start()
        for _ in range(n_workers)
    ]
    return meta, workers


def _ingest(meta, rows_sent, base, n, keys=23):
    rows = [((base + i) % keys, 7 * (base + i) + 1) for i in range(n)]
    vals = ",".join(f"({k},{v})" for k, v in rows)
    meta.execute_ddl(f"INSERT INTO t VALUES {vals}")
    rows_sent.extend(rows)


def _drive(meta, n, chunks=2):
    for _ in range(n):
        for _ in range(200):
            if meta.tick(chunks)["committed"]:
                break
        else:
            raise TimeoutError("round never committed")


def test_cluster_scale_out_in_converges(tmp_path):
    """Double then halve mid-stream: byte-identical convergence, only
    moved vnodes transferred, exchange flowing worker-to-worker."""
    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.sql.engine import Engine

    meta, workers = _mk_cluster(tmp_path)
    rows_sent: list = []
    try:
        meta.scale(1)
        for sql in DDL:
            meta.execute_ddl(sql)
        assert meta.state()["jobs"][0]["partitions"] is not None

        _ingest(meta, rows_sent, 0, 200)
        _drive(meta, 3)
        out = meta.scale(2)
        assert out["moved_vnodes"] == 8  # 16 vnodes, 1 -> 2: minimal
        ents = sum(t["entries"] for t in out["transfers"])
        assert 0 < ents < 2 * 23  # a strict slice (agg + mv entries)
        _ingest(meta, rows_sent, 200, 200)
        _drive(meta, 3)
        back = meta.scale(1)
        assert back["moved_vnodes"] == 8
        _ingest(meta, rows_sent, 400, 200)
        # drain: every ingested row accounted for
        for _ in range(200):
            meta.tick(2)
            _, rows = meta.serve(READ)
            if sum(int(r[1]) for r in rows) == len(rows_sent):
                break
        else:
            raise TimeoutError("cluster never drained")
        cluster = sorted(tuple(int(x) for x in r) for r in rows)

        # the peer exchange carried the follower's copy
        stats = {w.worker_id: w.client.call("scale_stats")
                 for w in meta.live_workers()}
        assert sum(s["exchange_rows_in"]
                   for s in stats.values()) > 0

        eng = Engine(RwConfig.from_dict(CONFIG))
        for sql in DDL:
            eng.execute(sql)
        vals = ",".join(f"({k},{v})" for k, v in rows_sent)
        eng.execute(f"INSERT INTO t VALUES {vals}")
        for _ in range(200):
            eng.tick(barriers=1, chunks_per_barrier=2)
            if sum(int(r[1]) for r in eng.execute(READ)) \
                    == len(rows_sent):
                break
        single = sorted(tuple(int(x) for x in r)
                        for r in eng.execute(READ))
        assert cluster == single
        # aggregate reads cannot union across partitions: loud refusal
        with pytest.raises(ValueError, match="partitioned"):
            meta.serve("SELECT sum(n) FROM agg")
    finally:
        for w in workers:
            w.stop()
        meta.stop()


#: join matrix entry sizing: the MV hash table needs headroom beyond
#: live rows — retraction churn leaves tombstoned slots behind
JOIN_CONFIG = {
    "streaming": {"chunk_size": 64},
    "state": {"agg_table_size": 1 << 8, "agg_emit_capacity": 128,
              "mv_table_size": 1 << 10, "mv_ring_size": 1 << 10},
    "storage": {"checkpoint_keep_epochs": 4},
}
JOIN_DDL = [
    "CREATE TABLE ja (k BIGINT, v BIGINT)",
    "CREATE TABLE jb (k BIGINT, w BIGINT)",
    """CREATE MATERIALIZED VIEW jmv AS
       SELECT ja.k AS k, ja.v AS v, jb.w AS w
       FROM ja LEFT JOIN jb ON ja.k = jb.k""",
]
JOIN_READ = "SELECT k, v, w FROM jmv"


def test_join_pool_scale_out_in_converges(tmp_path):
    """Exchange-lite matrix entry: a JOIN-pool job (both sides sliced
    on the join key into dense hash-join partitions) scaled 1 → 2 → 1
    mid-stream under RETRACTION churn (left-outer pads retracting as
    their matches arrive), byte-identical to single-node, with only
    the moved vnodes' entries transferred and ZERO device gate drops
    on the shuffled path."""
    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.sql.engine import Engine

    meta, workers = _mk_cluster(tmp_path, config=JOIN_CONFIG)
    a_rows: list = []
    b_rows: list = []
    try:
        meta.scale(1)
        for sql in JOIN_DDL:
            meta.execute_ddl(sql)
        job = meta.state()["jobs"][0]
        assert job["partitions"] is not None
        # both source edges compiled into the shuffle choreography
        ex = meta.state()["exchange"]["tables"]
        assert ex["ja"]["mode"] == "shuffle" and ex["ja"]["key_col"] == 0
        assert ex["jb"]["mode"] == "shuffle" and ex["jb"]["key_col"] == 0

        def ingest_a(base, n, keys=23):
            rows = [((base + i) % keys, 7 * (base + i) + 1)
                    for i in range(n)]
            meta.execute_ddl("INSERT INTO ja VALUES " + ",".join(
                f"({k},{v})" for k, v in rows))
            a_rows.extend(rows)

        def ingest_b(ks):
            rows = [(k, 1000 + 3 * k) for k in ks]
            meta.execute_ddl("INSERT INTO jb VALUES " + ",".join(
                f"({k},{w})" for k, w in rows))
            b_rows.extend(rows)

        # half the keys matched up front; the rest arrive mid-stream
        # (pad rows retract through both scale ops)
        ingest_b(range(0, 23, 2))
        ingest_a(0, 100)
        _drive(meta, 3)
        out = meta.scale(2)
        assert out["moved_vnodes"] == 8
        ents = sum(t["entries"] for t in out["transfers"])
        # a strict slice: join-side keys + MV rows of moved vnodes
        # only (never the whole keyspace twice over)
        assert 0 < ents < 2 * (23 + 100)
        ingest_b(range(1, 23, 2))     # RETRACTION churn while scaled
        ingest_a(100, 80)
        _drive(meta, 3)
        back = meta.scale(1)
        assert back["moved_vnodes"] == 8
        ingest_a(180, 40)
        for _ in range(200):
            meta.tick(2)
            _, rows = meta.serve(JOIN_READ)
            if len(rows) == len(a_rows) \
                    and all(r[2] is not None for r in rows):
                break
        else:
            raise TimeoutError("join cluster never drained")
        cluster = sorted(tuple(int(x) for x in r) for r in rows)

        # the shuffled path never dropped a row at a gate
        stats = {w.worker_id: w.client.call("scale_stats")
                 for w in meta.live_workers()}
        assert all(s["gate_dropped"] == 0 for s in stats.values())
        assert sum(s["exchange_rows_in"]
                   for s in stats.values()) > 0

        eng = Engine(RwConfig.from_dict(JOIN_CONFIG))
        for sql in JOIN_DDL:
            eng.execute(sql)
        b1 = [r for r in b_rows if r[0] % 2 == 0]
        b2 = [r for r in b_rows if r[0] % 2 == 1]
        eng.execute("INSERT INTO jb VALUES " + ",".join(
            f"({k},{w})" for k, w in b1))
        eng.execute("INSERT INTO ja VALUES " + ",".join(
            f"({k},{v})" for k, v in a_rows))
        eng.execute("INSERT INTO jb VALUES " + ",".join(
            f"({k},{w})" for k, w in b2))
        for _ in range(200):
            eng.tick(barriers=1, chunks_per_barrier=2)
            rows = eng.execute(JOIN_READ)
            if len(rows) == len(a_rows) \
                    and all(r[2] is not None for r in rows):
                break
        single = sorted(tuple(int(x) for x in r)
                        for r in eng.execute(JOIN_READ))
        assert cluster == single
    finally:
        for w in workers:
            w.stop()
        meta.stop()


def test_mv_on_mv_over_partitioned_upstream_converges(tmp_path):
    """MV-on-MV over a vnode-partitioned upstream: the attach edge
    compiles to the IDENTITY exchange (downstream keys carry the
    upstream distribution key), every partition attaches the same
    chain mid-stream, and both MVs converge byte-identical to a
    single node through a scale op.  Reduced-key shapes refuse."""
    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.sql.engine import Engine

    MV2 = ("CREATE MATERIALIZED VIEW agg2 AS "
           "SELECT k, n + 1 AS n1, s * 2 AS s2 FROM agg")
    meta, workers = _mk_cluster(tmp_path)
    rows_sent: list = []
    try:
        meta.scale(2)
        for sql in DDL:
            meta.execute_ddl(sql)
        _ingest(meta, rows_sent, 0, 150)
        _drive(meta, 3)
        # attach MID-STREAM on the partitioned upstream
        meta.execute_ddl(MV2)
        assert meta._mv_to_job["agg2"] == "agg"
        assert ("agg", "agg2") in meta.jobs["agg"].attach_edges
        _ingest(meta, rows_sent, 150, 150)
        _drive(meta, 3)
        back = meta.scale(1)
        assert back["moved_vnodes"] == 8
        _ingest(meta, rows_sent, 300, 100)
        for _ in range(200):
            meta.tick(2)
            _, rows = meta.serve(READ)
            if sum(int(r[1]) for r in rows) == len(rows_sent):
                break
        else:
            raise TimeoutError("never drained")
        cl1 = sorted(tuple(int(x) for x in r) for r in rows)
        cl2 = sorted(tuple(int(x) for x in r)
                     for r in meta.serve("SELECT k, n1, s2 "
                                         "FROM agg2")[1])

        eng = Engine(RwConfig.from_dict(CONFIG))
        for sql in DDL:
            eng.execute(sql)
        eng.execute(MV2)
        eng.execute("INSERT INTO t VALUES " + ",".join(
            f"({k},{v})" for k, v in rows_sent))
        for _ in range(200):
            eng.tick(barriers=1, chunks_per_barrier=2)
            if sum(int(r[1]) for r in eng.execute(READ)) \
                    == len(rows_sent):
                break
        assert cl1 == sorted(tuple(int(x) for x in r)
                             for r in eng.execute(READ))
        assert cl2 == sorted(
            tuple(int(x) for x in r)
            for r in eng.execute("SELECT k, n1, s2 FROM agg2"))
        # reduced keys refuse loudly (cross-partition attach exchange
        # is the next round)
        with pytest.raises(Exception, match="next round|group"):
            meta.execute_ddl(
                "CREATE MATERIALIZED VIEW bad AS "
                "SELECT s % 3 AS g, count(*) AS c FROM agg "
                "GROUP BY s % 3"
            )
    finally:
        for w in workers:
            w.stop()
        meta.stop()


def test_meta_restart_recovers_partitions(tmp_path):
    """A restarted meta replays the scale log and re-adopts every
    partition LINEAGE from the shared store — rounds resume and the
    MV survives byte-identically."""
    from risingwave_tpu.cluster import MetaService

    meta, workers = _mk_cluster(tmp_path)
    rows_sent: list = []
    try:
        meta.scale(2)
        for sql in DDL:
            meta.execute_ddl(sql)
        _ingest(meta, rows_sent, 0, 150)
        _drive(meta, 3)
        _, rows = meta.serve(READ)
        before = sorted(tuple(int(x) for x in r) for r in rows)
        n_parts = len(meta.state()["jobs"][0]["partitions"])
        assert n_parts == 2
        meta.stop()

        meta2 = MetaService(str(tmp_path), heartbeat_timeout_s=60.0)
        meta2.start(port=0, monitor=False)
        try:
            assert meta2.recovered
            assert meta2.scale_partitioning  # from the scale log
            job = meta2.jobs["agg"]
            assert job.partitions is not None
            # workers re-register (their heartbeat loops are against
            # the DEAD meta's port — drive re-registration directly)
            for w in workers:
                w._meta_client.close()
                w._meta_client.port = meta2.rpc_port
                w._register()
            meta2._assign_pending()
            assert all(p.worker_id is not None
                       for p in job.partitions.values())
            _drive(meta2, 2)
            _, rows = meta2.serve(READ)
            after = sorted(tuple(int(x) for x in r) for r in rows)
            assert after == before
        finally:
            meta2.stop()
    finally:
        for w in workers:
            w.stop()


def test_merge_failover_when_no_spare_worker(tmp_path):
    """ROADMAP remaining item: a partitioned job's worker dies and NO
    spare worker can host its lineage — the dead partition's vnodes
    MERGE into the survivor via the scale-in slice-transplant path
    (recipient rewinds to the last committed round, transplants the
    dead lineage's slice, widens its mask) instead of stalling the
    round forever.  Rounds resume and the MV converges
    byte-identically."""
    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.sql.engine import Engine

    meta, workers = _mk_cluster(tmp_path, n_workers=2)
    rows_sent: list = []
    try:
        meta.scale(2)
        for sql in DDL:
            meta.execute_ddl(sql)
        _ingest(meta, rows_sent, 0, 160)
        _drive(meta, 3)
        job = meta.jobs["agg"]
        assert len(job.partitions) == 2

        # kill one worker (no spare exists: both host a partition)
        dead = workers[1]
        dead_id = dead.worker_id
        dead.stop()
        meta._on_worker_dead(meta.workers[dead_id])
        meta._assign_pending()

        # the dead partition MERGED into the survivor
        assert len(job.partitions) == 1
        survivor = next(iter(job.partitions.values()))
        assert sorted(survivor.vnodes) == list(range(meta.n_vnodes))
        assert survivor.worker_id == workers[0].worker_id
        assert meta.metrics.get("cluster_merge_failovers_total") == 1
        assert all(w == workers[0].worker_id for w in meta.vnode_map)

        # rounds resume; ingest keeps flowing; everything drains
        _ingest(meta, rows_sent, 160, 160)
        for _ in range(200):
            meta.tick(2)
            _, rows = meta.serve(READ)
            if sum(int(r[1]) for r in rows) == len(rows_sent):
                break
        else:
            raise TimeoutError("merged cluster never drained")
        cluster = sorted(tuple(int(x) for x in r) for r in rows)

        eng = Engine(RwConfig.from_dict(CONFIG))
        for sql in DDL:
            eng.execute(sql)
        vals = ",".join(f"({k},{v})" for k, v in rows_sent)
        eng.execute(f"INSERT INTO t VALUES {vals}")
        for _ in range(200):
            eng.tick(barriers=1, chunks_per_barrier=2)
            if sum(int(r[1]) for r in eng.execute(READ)) \
                    == len(rows_sent):
                break
        single = sorted(tuple(int(x) for x in r)
                        for r in eng.execute(READ))
        assert cluster == single
    finally:
        for w in workers:
            w.stop()
        meta.stop()
