"""Slow wrapper over scripts/cluster_stress.py (the ISSUE 3 acceptance
harness), matching the compaction_stress pattern."""

import pytest


@pytest.mark.slow
def test_cluster_stress_short(tmp_path):
    import importlib
    import sys

    sys.path.insert(0, "scripts")
    try:
        cs = importlib.import_module("cluster_stress")
    finally:
        sys.path.pop(0)

    summary = cs.run(rounds=10, workers=2, kill_at_round=4,
                     readers=2, data_dir=str(tmp_path))
    assert summary["read_errors"] == 0, summary["read_error_samples"]
    assert summary["mv_mismatches"] == 0
    assert summary["failovers"] == 1
    assert summary["rounds_committed"] == summary["rounds"]
    assert summary["reads"] > 0
