"""Round-4 conformance features: varchar auto-width, to_char,
split_part/replace, FILTER aggregates, and the row_number-in-subquery
GroupTopN rewrite (nexmark q9/q10/q17/q18/q19/q20/q22 shapes).

Ref: e2e_test/streaming/nexmark/views/*.slt.part — the shapes tested
here mirror the reference corpus queries these features unlock.
"""

import numpy as np
import pytest

from risingwave_tpu.sql import Engine
from risingwave_tpu.sql.planner import PlannerConfig


def small_engine() -> Engine:
    return Engine(PlannerConfig(
        chunk_capacity=128,
        agg_table_size=1 << 10, agg_emit_capacity=1 << 9,
        join_table_size=1 << 9, join_bucket_cap=16,
        join_out_capacity=1 << 11, join_pool_size=1 << 11,
        mv_table_size=1 << 10, mv_ring_size=1 << 12,
        topn_pool_size=1 << 9, topn_emit_capacity=1 << 8,
    ))


BID_DDL = ("CREATE TABLE bid (auction BIGINT, bidder BIGINT, "
           "price BIGINT, channel VARCHAR, url VARCHAR, "
           "date_time TIMESTAMP, extra VARCHAR)")


def test_varchar_auto_width_no_truncation():
    """q20 regression: undeclared VARCHAR sizes from observed data."""
    eng = small_engine()
    eng.execute("CREATE TABLE t (id BIGINT, s VARCHAR)")
    long = "z" * 300
    eng.execute(f"INSERT INTO t VALUES (1, '{long}')")
    eng.execute("CREATE MATERIALIZED VIEW mv AS SELECT id, s FROM t")
    eng.tick(barriers=2)
    (row,) = eng.execute("SELECT * FROM mv")
    assert row[1] == long


def test_varchar_overflow_after_compile_is_loud():
    eng = small_engine()
    eng.execute("CREATE TABLE t (id BIGINT, s VARCHAR)")
    eng.execute("INSERT INTO t VALUES (1, 'short')")
    eng.execute("CREATE MATERIALIZED VIEW mv AS SELECT id, s FROM t")
    with pytest.raises(ValueError, match="exceeds the width"):
        eng.execute(f"INSERT INTO t VALUES (2, '{'y' * 500}')")


def test_declared_varchar_width_is_respected():
    eng = small_engine()
    eng.execute("CREATE TABLE t (id BIGINT, s VARCHAR(8))")
    eng.execute("INSERT INTO t VALUES (1, 'fits')")
    eng.execute("CREATE MATERIALIZED VIEW mv AS SELECT s FROM t")
    eng.tick(barriers=2)
    assert eng.execute("SELECT * FROM mv") == [("fits",)]


def test_to_char_and_split_part_q10_q22():
    eng = small_engine()
    eng.execute(BID_DDL)
    eng.execute("INSERT INTO bid VALUES (1,1,100,'Google',"
                "'https://x.com/a/bb/item.htm?q=1',"
                "'2015-07-15 13:05:07.123','x')")
    eng.execute(
        "CREATE MATERIALIZED VIEW v AS SELECT auction, "
        "to_char(date_time, 'YYYY-MM-DD') AS d, "
        "to_char(date_time, 'HH:MI') AS t12, "
        "to_char(date_time, 'HH24:MI:SS.MS') AS t24, "
        "split_part(url, '/', 4) AS dir1, "
        "split_part(url, '/', -1) AS last, "
        "replace(channel, 'o', '0') AS ch, "
        "length(channel) AS n FROM bid"
    )
    eng.tick(barriers=2)
    (r,) = eng.execute("SELECT * FROM v")
    assert r[1:] == ("2015-07-15", "01:05", "13:05:07.123",
                     "a", "item.htm?q=1", "G00gle", 6)


def test_filter_clause_aggregates_q17():
    eng = small_engine()
    eng.execute(BID_DDL)
    prices = [500, 20_000, 2_000_000, 800, 5_000_000, 15_000]
    for i, p in enumerate(prices):
        eng.execute(f"INSERT INTO bid VALUES (7,{i},{p},'c','u',"
                    f"'2015-07-15 00:00:{i:02d}','x')")
    eng.execute(
        "CREATE MATERIALIZED VIEW v AS SELECT auction, "
        "count(*) AS total, "
        "count(*) filter (where price < 10000) AS r1, "
        "count(*) filter (where price >= 10000 and price < 1000000) AS r2, "
        "count(*) filter (where price >= 1000000) AS r3, "
        "sum(price) filter (where price < 10000) AS s1, "
        "max(price) filter (where price > 99999999) AS m_none "
        "FROM bid GROUP BY auction"
    )
    eng.tick(barriers=2)
    (r,) = eng.execute("SELECT * FROM v")
    assert r == (7, 6, 2, 2, 2, 1300, None)


def test_group_topn_rewrite_q18_shape():
    eng = small_engine()
    eng.execute(BID_DDL)
    rng = np.random.default_rng(5)
    rows = []
    for i in range(30):
        a, b = int(rng.integers(0, 3)), int(rng.integers(0, 2))
        rows.append((a, b, i))
        eng.execute(f"INSERT INTO bid VALUES ({a},{b},{i},'c','u',"
                    f"'2015-07-15 00:00:{i % 60:02d}','e{i}')")
    eng.execute(
        "CREATE MATERIALIZED VIEW v AS SELECT auction, bidder, price "
        "FROM (SELECT *, ROW_NUMBER() OVER (PARTITION BY bidder, auction "
        "ORDER BY date_time DESC, extra) AS rank_number FROM bid) "
        "WHERE rank_number <= 1"
    )
    eng.tick(barriers=2)
    got = sorted(tuple(map(int, r)) for r in
                 eng.execute("SELECT * FROM v"))
    best = {}
    for a, b, i in rows:
        k = (b, a)
        key = (-(i % 60), f"e{i}")
        if k not in best or key < best[k][0]:
            best[k] = (key, (a, b, i))
    assert got == sorted(v[1] for v in best.values())


def test_group_topn_rank_output_q19_shape():
    """SELECT * over the subquery includes the rank column."""
    eng = small_engine()
    eng.execute(BID_DDL)
    rng = np.random.default_rng(9)
    rows = []
    for i in range(40):
        a, p = int(rng.integers(0, 3)), int(rng.integers(1, 10**6))
        rows.append((a, p))
        eng.execute(f"INSERT INTO bid VALUES ({a},0,{p},'c','u',"
                    f"'2015-07-15 00:00:00','x')")
    eng.execute(
        "CREATE MATERIALIZED VIEW v AS SELECT * FROM "
        "(SELECT *, ROW_NUMBER() OVER (PARTITION BY auction "
        "ORDER BY price DESC) AS rank_number FROM bid) "
        "WHERE rank_number <= 5"
    )
    eng.tick(barriers=2)
    got = eng.execute("SELECT auction, price, rank_number FROM v")
    import collections
    groups = collections.defaultdict(list)
    for a, p in rows:
        groups[a].append(p)
    want = []
    for a, ps in groups.items():
        for rk, p in enumerate(sorted(ps, reverse=True)[:5], 1):
            want.append((a, p, rk))
    assert sorted(tuple(map(int, r)) for r in got) == sorted(want)


def test_group_topn_rank_updates_on_displacement():
    """A new high row displaces ranks; the MV must follow."""
    eng = small_engine()
    eng.execute("CREATE TABLE t (g BIGINT, v BIGINT)")
    for x in (10, 30):
        eng.execute(f"INSERT INTO t VALUES (1, {x})")
    eng.execute(
        "CREATE MATERIALIZED VIEW v AS SELECT * FROM "
        "(SELECT *, ROW_NUMBER() OVER (PARTITION BY g ORDER BY v DESC) "
        "AS rn FROM t) WHERE rn <= 2"
    )
    eng.tick(barriers=2)
    assert sorted(eng.execute("SELECT v, rn FROM v")) == [(10, 2), (30, 1)]
    eng.execute("INSERT INTO t VALUES (1, 99)")  # displaces 10, shifts 30
    eng.tick(barriers=2)
    assert sorted(eng.execute("SELECT v, rn FROM v")) == [(30, 2), (99, 1)]


def test_parse_rows_between_frame():
    from risingwave_tpu.sql.parser import parse
    s = parse("SELECT AVG(x) OVER (PARTITION BY g ORDER BY t "
              "ROWS BETWEEN 10 PRECEDING AND CURRENT ROW) FROM t")[0]
    w = s.items[0].expr
    assert w.frame == (10, 0)


def test_distinct_mixed_with_filters_q15_shape():
    eng = small_engine()
    eng.execute("CREATE TABLE bid (auction BIGINT, bidder BIGINT, "
                "price BIGINT)")
    rows = [(1, b, p) for b, p in
            [(0, 500), (0, 20000), (1, 500), (1, 500), (2, 2000000),
             (3, 20000), (3, 500), (4, 20000)]]
    for a, b, p in rows:
        eng.execute(f"INSERT INTO bid VALUES ({a},{b},{p})")
    eng.execute(
        "CREATE MATERIALIZED VIEW v AS SELECT auction, "
        "count(*) AS total, "
        "count(distinct bidder) AS bidders, "
        "count(distinct bidder) filter (where price < 10000) AS b1, "
        "count(distinct bidder) filter (where price >= 10000) AS b2, "
        "sum(distinct price) AS sp "
        "FROM bid GROUP BY auction"
    )
    eng.tick(barriers=2)
    (r,) = eng.execute("SELECT * FROM v")
    # b1: bidders {0,1,3} with price<10000; b2: {0,2,3,4} >= 10000
    assert r == (1, 8, 5, 3, 4, 500 + 20000 + 2000000)


def test_distinct_retracts_on_deletes():
    """Retractable input: distinct counts fall when the last copy of a
    value retracts (counted dedup state, ref distinct.rs)."""
    eng = small_engine()
    eng.execute("CREATE TABLE auction (id BIGINT, cat BIGINT, "
                "PRIMARY KEY (id))")
    eng.execute("CREATE TABLE bid (auction BIGINT, bidder BIGINT)")
    eng.execute("INSERT INTO auction VALUES (1, 10)")
    for b in (7, 7, 8):
        eng.execute(f"INSERT INTO bid VALUES (1, {b})")
    # the join output retracts when auction rows change; distinct
    # bidder count rides the transitions
    eng.execute(
        "CREATE MATERIALIZED VIEW v AS SELECT COUNT(DISTINCT b.bidder) "
        "AS db FROM auction a JOIN bid b ON a.id = b.auction"
    )
    eng.tick(barriers=2)
    assert eng.execute("SELECT * FROM v") == [(2,)]
    eng.execute("INSERT INTO bid VALUES (1, 9)")
    eng.tick(barriers=2)
    assert eng.execute("SELECT * FROM v") == [(3,)]


def test_in_and_not_in_subquery_q103_q104():
    eng = small_engine()
    eng.execute("CREATE TABLE auction (id BIGINT, item_name VARCHAR, "
                "PRIMARY KEY (id))")
    eng.execute("CREATE TABLE bid (auction BIGINT, bidder BIGINT)")
    for aid in range(5):
        eng.execute(f"INSERT INTO auction VALUES ({aid},'i{aid}')")
    for a, n in ((0, 3), (1, 1), (2, 2)):
        for i in range(n):
            eng.execute(f"INSERT INTO bid VALUES ({a},{i})")
    eng.execute(
        "CREATE MATERIALIZED VIEW v103 AS SELECT a.id AS aid FROM "
        "auction a WHERE a.id IN (SELECT b.auction FROM bid b "
        "GROUP BY b.auction HAVING COUNT(*) >= 2)"
    )
    eng.execute(
        "CREATE MATERIALIZED VIEW v104 AS SELECT a.id AS aid FROM "
        "auction a WHERE a.id NOT IN (SELECT b.auction FROM bid b "
        "GROUP BY b.auction HAVING COUNT(*) < 2)"
    )
    eng.tick(barriers=2)
    assert sorted(eng.execute("SELECT aid FROM v103")) == [(0,), (2,)]
    assert sorted(eng.execute("SELECT aid FROM v104")) == \
        [(0,), (2,), (3,), (4,)]
    eng.execute("INSERT INTO bid VALUES (1, 9)")  # auction 1 now has 2
    eng.tick(barriers=2)
    assert sorted(eng.execute("SELECT aid FROM v103")) == \
        [(0,), (1,), (2,)]
    assert sorted(eng.execute("SELECT aid FROM v104")) == \
        [(0,), (1,), (2,), (3,), (4,)]


def test_scalar_subquery_having_dynamic_filter_q102():
    eng = small_engine()
    eng.execute("CREATE TABLE auction (id BIGINT, item_name VARCHAR, "
                "PRIMARY KEY (id))")
    eng.execute("CREATE TABLE bid (auction BIGINT, bidder BIGINT)")
    for aid in range(4):
        eng.execute(f"INSERT INTO auction VALUES ({aid},'i{aid}')")
    for a, n in ((0, 5), (1, 1), (2, 3), (3, 2)):
        for i in range(n):
            eng.execute(f"INSERT INTO bid VALUES ({a},{i})")
    eng.execute(
        "CREATE MATERIALIZED VIEW v AS SELECT a.id AS aid, "
        "COUNT(b.auction) AS bc FROM auction a JOIN bid b "
        "ON a.id = b.auction GROUP BY a.id, a.item_name "
        "HAVING COUNT(b.auction) >= "
        "(SELECT COUNT(*) / COUNT(DISTINCT auction) FROM bid)"
    )
    eng.tick(barriers=2)
    # 11 bids / 4 auctions = 2 -> {0:5, 2:3, 3:2}
    assert sorted(eng.execute("SELECT aid, bc FROM v")) == \
        [(0, 5), (2, 3), (3, 2)]
    # threshold moves up; previously-passing groups must retract
    for i in range(9):
        eng.execute(f"INSERT INTO bid VALUES (1, {100 + i})")
    eng.tick(barriers=2)
    # 20 bids / 4 = 5 -> {0:5, 1:10}
    assert sorted(eng.execute("SELECT aid, bc FROM v")) == \
        [(0, 5), (1, 10)]


def test_sql_udf_inline_q14():
    eng = small_engine()
    eng.execute("CREATE TABLE t (s VARCHAR, c VARCHAR)")
    eng.execute("INSERT INTO t VALUES ('accbcac', 'c')")
    eng.execute(
        "CREATE FUNCTION count_char(s varchar, c varchar) RETURNS int "
        "LANGUAGE SQL AS $$SELECT LENGTH(s) - LENGTH(REPLACE(s, c, ''))$$"
    )
    eng.execute("CREATE MATERIALIZED VIEW v AS "
                "SELECT count_char(s, c) AS n FROM t")
    eng.tick(barriers=2)
    assert eng.execute("SELECT * FROM v") == [(4,)]


def test_sql_udf_duplicate_and_arity_errors():
    import pytest
    eng = small_engine()
    eng.execute("CREATE FUNCTION one(x int) RETURNS int "
                "LANGUAGE SQL AS 'SELECT x + 1'")
    with pytest.raises(ValueError, match="already exists"):
        eng.execute("CREATE FUNCTION one(x int) RETURNS int "
                    "LANGUAGE SQL AS 'SELECT x'")
    eng.execute("CREATE TABLE t (a BIGINT)")
    with pytest.raises(ValueError, match="takes 1 arguments"):
        eng.execute("CREATE MATERIALIZED VIEW v AS "
                    "SELECT one(a, a) AS n FROM t")
