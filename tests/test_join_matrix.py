"""Full join matrix tests: outer / semi / anti with retractions,
count-based degree transitions, and windowed (lossless) emission.

Reference counterparts: hash_join.rs:158 (JoinTypePrimitive matrix,
degree tables), dispatch.rs:949-1010 (U-pair consumers).
Ground truth: a brute-force python join over the live multisets after
every chunk — the folded output changelog must always equal it.
"""

from collections import Counter

import jax.numpy as jnp
import numpy as np
import pytest

import risingwave_tpu  # noqa: F401
from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.expr.node import InputRef
from risingwave_tpu.stream.hash_join import HashJoinExecutor

LS = Schema((Field("k", DataType.INT64), Field("a", DataType.INT64)))
RS = Schema((Field("k", DataType.INT64), Field("b", DataType.INT64)))


def make_chunk(schema, rows, ops):
    n = max(len(rows), 1)
    cols = tuple(
        jnp.asarray([r[i] for r in rows] or [0], jnp.int64)
        for i in range(2)
    )
    return Chunk(
        cols,
        jnp.asarray(ops or [0], jnp.int8),
        jnp.asarray([True] * len(rows) or [False], jnp.bool_),
        schema,
    )


def fold(acc: Counter, out: Chunk):
    """Fold an emitted changelog chunk into a multiset of rows."""
    vis = np.asarray(out.valid)
    ops = np.asarray(out.ops)[vis]
    cols = []
    for c in out.columns:
        from risingwave_tpu.common.chunk import split_col
        data, null = split_col(c)
        vals = np.asarray(data)[vis]
        if null is not None:
            nl = np.asarray(null)[vis]
            cols.append([None if nl[i] else int(vals[i])
                         for i in range(len(vals))])
        else:
            cols.append([int(v) for v in vals])
    for i in range(len(ops)):
        row = tuple(c[i] for c in cols)
        acc[row] += 1 if ops[i] in (0, 3) else -1
    return acc


def expected(join_type, left_rows, right_rows):
    """Brute-force expected multiset for the current live rows."""
    out = Counter()
    if join_type in ("inner", "left_outer", "right_outer", "full_outer"):
        for lk, la in left_rows:
            for rk, rb in right_rows:
                if lk == rk:
                    out[(lk, la, rk, rb)] += 1
        if join_type in ("left_outer", "full_outer"):
            for lk, la in left_rows:
                if not any(rk == lk for rk, _ in right_rows):
                    out[(lk, la, None, None)] += 1
        if join_type in ("right_outer", "full_outer"):
            for rk, rb in right_rows:
                if not any(lk == rk for lk, _ in left_rows):
                    out[(None, None, rk, rb)] += 1
        return out
    side_rows = left_rows if join_type.startswith("left") else right_rows
    other = right_rows if join_type.startswith("left") else left_rows
    anti = join_type.endswith("anti")
    for k, v in side_rows:
        matched = any(ok == k for ok, _ in other)
        if matched != anti:
            out[(k, v)] += 1
    return out


SCRIPT = [
    # (side, rows, ops)  0=insert 1=delete
    ("left", [(1, 10)], [0]),
    ("right", [(1, 100), (2, 200)], [0, 0]),
    ("left", [(2, 20), (3, 30)], [0, 0]),
    ("right", [(1, 101), (3, 300)], [0, 0]),
    ("right", [(1, 100)], [1]),            # retract a match
    ("left", [(1, 10)], [1]),              # retract a probe row
    ("right", [(1, 101)], [1]),            # key 1 right side empties
    ("left", [(4, 40), (4, 41)], [0, 0]),  # unmatched pair of rows
    ("right", [(4, 400)], [0]),            # both transition together
    ("right", [(4, 400)], [1]),            # and back
    ("left", [(5, 50), (5, 50)], [0, 1]),  # in-chunk annihilation
]


@pytest.mark.parametrize("join_type", [
    "inner", "left_outer", "right_outer", "full_outer",
    "left_semi", "left_anti", "right_semi", "right_anti",
])
def test_join_type_ground_truth(join_type):
    j = HashJoinExecutor(
        LS, RS, [InputRef(0)], [InputRef(0)],
        table_size=64, bucket_cap=8, out_capacity=256,
        join_type=join_type,
    )
    st = j.init_state()
    acc = Counter()
    left_rows, right_rows = [], []
    for side, rows, ops in SCRIPT:
        live = left_rows if side == "left" else right_rows
        for r, o in zip(rows, ops):
            if o == 0:
                live.append(r)
            else:
                live.remove(r)
        schema = LS if side == "left" else RS
        st, out = j.apply(st, make_chunk(schema, rows, ops), side)
        fold(acc, out)
        want = expected(join_type, left_rows, right_rows)
        got = +acc  # drop zero entries
        assert got == +want, (
            f"{join_type} after {side} {rows} {ops}: {got} != {+want}"
        )
    assert int(st.emit_overflow) == 0
    assert int(st.left.inconsistency) == 0
    assert int(st.right.inconsistency) == 0


def test_windowed_emission_losslessness():
    """A tiny out_capacity with windowed emission yields the same fold
    as one giant window (the DagJob path drops nothing)."""
    def run(out_capacity, windowed):
        j = HashJoinExecutor(
            LS, RS, [InputRef(0)], [InputRef(0)],
            table_size=64, bucket_cap=16, out_capacity=out_capacity,
            join_type="full_outer",
        )
        st = j.init_state()
        acc = Counter()
        for side, rows, ops in SCRIPT:
            schema = LS if side == "left" else RS
            chunk = make_chunk(schema, rows, ops)
            if windowed:
                st, pend = j.apply_begin(st, chunk, side)
                build = j.build_rows_of(st, side)
                for w in range(j.max_windows(chunk.capacity)):
                    fold(acc, j.emit_window(
                        build, pend, jnp.int32(w), side
                    )[0])
            else:
                st, out = j.apply(st, chunk, side)
                fold(acc, out)
        return +acc

    assert run(4, windowed=True) == run(4096, windowed=False)


def test_null_join_keys_never_match():
    """SQL join semantics: a NULL key matches nothing — it pads on the
    preserved side and never pairs."""
    from risingwave_tpu.common.chunk import NCol

    nls = Schema((Field("k", DataType.INT64, nullable=True),
                  Field("a", DataType.INT64)))
    j = HashJoinExecutor(
        nls, RS, [InputRef(0)], [InputRef(0)],
        table_size=64, bucket_cap=8, out_capacity=64,
        join_type="left_outer",
    )
    st = j.init_state()
    chunk = Chunk(
        (NCol(jnp.asarray([1, 1], jnp.int64),
              jnp.asarray([False, True], jnp.bool_)),
         jnp.asarray([10, 11], jnp.int64)),
        jnp.zeros((2,), jnp.int8),
        jnp.ones((2,), jnp.bool_),
        nls,
    )
    st, out = j.apply(st, make_chunk(RS, [(1, 100)], [0]), "right")
    st, out = j.apply(st, chunk, "left")
    acc = fold(Counter(), out)
    # row with k=1 pairs; row with k=NULL pads
    assert acc == Counter({(1, 10, 1, 100): 1, (None, 11, None, None): 1})


def test_sql_left_outer_join_mv():
    """LEFT OUTER JOIN end-to-end through SQL: pads appear, retract on
    first match, and reappear when the match disappears."""
    from tests.test_dag import small_engine

    eng = small_engine()
    eng.execute("CREATE TABLE l (k BIGINT, a BIGINT);")
    eng.execute("CREATE TABLE r (k BIGINT, b BIGINT);")
    eng.execute("""
        CREATE MATERIALIZED VIEW lo AS
        SELECT l.k AS k, l.a AS a, r.b AS b
        FROM l LEFT OUTER JOIN r ON l.k = r.k;
    """)
    eng.execute("INSERT INTO l VALUES (1, 10), (2, 20)")
    eng.tick(barriers=2, chunks_per_barrier=1)
    assert sorted(eng.execute("SELECT * FROM lo")) == [
        (1, 10, None), (2, 20, None)]
    eng.execute("INSERT INTO r VALUES (1, 100)")
    eng.tick(barriers=2, chunks_per_barrier=1)
    assert sorted(eng.execute("SELECT * FROM lo")) == [
        (1, 10, 100), (2, 20, None)]


def test_sql_full_outer_join_agg():
    """Aggregation over a FULL OUTER JOIN (pads count as NULL groups)."""
    from tests.test_dag import small_engine

    eng = small_engine()
    eng.execute("CREATE TABLE l (k BIGINT, a BIGINT);")
    eng.execute("CREATE TABLE r (k BIGINT, b BIGINT);")
    eng.execute("""
        CREATE MATERIALIZED VIEW fo AS
        SELECT count(*) AS rows
        FROM l FULL OUTER JOIN r ON l.k = r.k;
    """)
    eng.execute("INSERT INTO l VALUES (1, 10), (2, 20)")
    eng.execute("INSERT INTO r VALUES (2, 200), (3, 300)")
    eng.tick(barriers=2, chunks_per_barrier=1)
    # (1,10,NULL) + (2,20,200) + (NULL,3,300) = 3 rows
    assert eng.execute("SELECT * FROM fo") == [(3,)]


def test_pad_retraction_orders_before_pair_insert():
    """Regression: when a projection collapses the pad row and the pair
    row to identical values, the section order [up-trans | pairs] must
    leave the row PRESENT in a whole-row-keyed MV (last-op-wins)."""
    from tests.test_dag import small_engine

    eng = small_engine()
    eng.execute("CREATE TABLE l (k BIGINT, a BIGINT);")
    eng.execute("CREATE TABLE r (k BIGINT, b BIGINT);")
    eng.execute("""
        CREATE MATERIALIZED VIEW lo AS
        SELECT l.a AS a FROM l LEFT OUTER JOIN r ON l.k = r.k;
    """)
    eng.execute("INSERT INTO l VALUES (1, 10)")
    eng.tick(barriers=2, chunks_per_barrier=1)
    assert eng.execute("SELECT * FROM lo") == [(10,)]  # the pad
    eng.execute("INSERT INTO r VALUES (1, 100)")
    eng.tick(barriers=2, chunks_per_barrier=1)
    # pad (10) retracted, pair (10) inserted — identical projected rows;
    # wrong section order would leave the MV empty
    assert eng.execute("SELECT * FROM lo") == [(10,)]
    eng.execute("INSERT INTO r VALUES (1, 101)")
    eng.tick(barriers=2, chunks_per_barrier=1)
    # two pairs now project to two identical (10) rows — whole-row pk
    # collapses them (documented set semantics); row stays present
    assert eng.execute("SELECT * FROM lo") == [(10,)]
