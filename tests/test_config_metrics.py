"""Config layers, metrics, EXPLAIN, engine-level durability/recovery."""

import numpy as np
import pytest

from risingwave_tpu.common.config import (
    RwConfig,
    SessionConfig,
    SystemParams,
)
from risingwave_tpu.common.metrics import MetricsRegistry
from risingwave_tpu.sql import Engine
from risingwave_tpu.sql.planner import PlannerConfig


def test_rw_config_from_dict():
    cfg = RwConfig.from_dict({
        "streaming": {"chunk_size": 1024},
        "state": {"agg_table_size": 256},
    })
    assert cfg.streaming.chunk_size == 1024
    assert cfg.state.agg_table_size == 256
    with pytest.raises(KeyError):
        RwConfig.from_dict({"streaming": {"nope": 1}})


def test_system_params_mutability():
    sp = SystemParams()
    assert sp.get("barrier_interval_ms") == 1000
    sp.set("checkpoint_frequency", 5)
    assert sp.get("checkpoint_frequency") == 5
    with pytest.raises(KeyError):
        sp.set("unknown", 1)


def test_session_config():
    sc = SessionConfig()
    sc.set("query_epoch", 42)
    assert sc.get("query_epoch") == 42
    assert any(k == "timezone" for k, _, _ in sc.show_all())


def test_metrics_registry():
    m = MetricsRegistry()
    m.inc("rows", 10, job="a")
    m.inc("rows", 5, job="a")
    m.set_gauge("epoch", 7, job="a")
    m.observe("lat", 0.003, job="a")
    m.observe("lat", 0.2, job="a")
    assert m.get("rows", job="a") == 15
    assert m.get("epoch", job="a") == 7
    assert m.quantile("lat", 0.5, job="a") <= 0.005
    text = m.render_prometheus()
    assert 'rows{job="a"} 15' in text
    assert "lat_count" in text


def test_engine_set_show_explain():
    eng = Engine(PlannerConfig(chunk_capacity=64))
    eng.execute("""
        CREATE SOURCE t (k BIGINT, v BIGINT) WITH (connector='datagen');
    """)
    eng.execute("SET query_epoch = 9")
    assert eng.session_config.get("query_epoch") == 9
    eng.execute("ALTER SYSTEM SET checkpoint_frequency = 3")
    assert eng.system_params.get("checkpoint_frequency") == 3
    params = eng.execute("SHOW PARAMETERS")
    assert any(row[0] == "barrier_interval_ms" for row in params)

    plan = eng.execute(
        "EXPLAIN SELECT k, count(*) FROM t GROUP BY k"
    )
    text = "\n".join(r[0] for r in plan)
    assert "HashAggExecutor" in text and "MaterializeExecutor" in text


def test_engine_durable_recovery(tmp_path):
    """Engine restart: catalog re-created via DDL, state via recover()."""
    ddl = """
        CREATE SOURCE t (k BIGINT, v BIGINT) WITH (connector='datagen');
        CREATE MATERIALIZED VIEW m AS
        SELECT k % 2 AS b, count(*) AS n FROM t GROUP BY k % 2;
    """
    cfg = PlannerConfig(chunk_capacity=64, agg_table_size=256,
                        agg_emit_capacity=64, mv_table_size=256)
    eng = Engine(cfg, data_dir=str(tmp_path))
    eng.execute(ddl)
    eng.tick(barriers=2, chunks_per_barrier=1)
    want = sorted(eng.execute("SELECT b, n FROM m"))

    # restart: the fresh engine bootstraps DDL + state from data_dir
    eng2 = Engine(cfg, data_dir=str(tmp_path))
    assert sorted(eng2.execute("SELECT b, n FROM m")) == want
    # continues from the checkpointed source offset, not from zero
    eng2.tick(barriers=1, chunks_per_barrier=1)
    rows = dict(eng2.execute("SELECT b, n FROM m"))
    assert rows[0] + rows[1] == 3 * 64


def test_engine_metrics_populated():
    eng = Engine(PlannerConfig(chunk_capacity=64))
    eng.execute("""
        CREATE SOURCE t (k BIGINT) WITH (connector='datagen');
        CREATE MATERIALIZED VIEW m AS SELECT k FROM t;
    """)
    eng.tick(barriers=2, chunks_per_barrier=1)
    assert eng.metrics.get("stream_rows_total", job="m") >= 128
    assert eng.metrics.get("committed_epoch", job="m") > 0


def test_metrics_timer_context():
    m = MetricsRegistry()
    with m.timer("op_seconds", stage="merge"):
        pass
    assert m.quantile("op_seconds", 0.5, stage="merge") <= 0.005
    assert "op_seconds_count" in m.render_prometheus()


def test_storage_service_metrics_and_exporter(tmp_path):
    """Compactor/GC/stall/bloom metrics flow into the engine registry
    and out the Prometheus text exporter (ISSUE 1 satellite)."""
    import struct

    eng = Engine(PlannerConfig(chunk_capacity=64),
                 data_dir=str(tmp_path))
    h = eng.hummock
    h.l0_trigger = 2
    h.stall_l0 = 3
    for i in range(4):
        h.write_batch([(struct.pack(">I", j), b"v")
                       for j in range(i, i + 20)], epoch=i + 1)
    h.wait_below_stall(timeout=0.02)      # times out: records stall
    while h.compact_once():
        pass
    assert h.get(struct.pack(">I", 0)) == b"v"
    assert h.get(struct.pack(">I", 999)) is None
    eng.storage_vacuum()

    m = eng.metrics
    # 4 ingest uploads + the compaction outputs
    assert m.get("storage_sst_uploads_total") >= 4
    assert m.get("storage_compaction_tasks_total", level="0") >= 1
    assert m.get("storage_compaction_bytes_total") > 0
    assert m.get("storage_gc_objects_total") >= 1
    assert m.get("storage_write_stall_seconds_total") > 0
    assert m.get("storage_l0_runs") == 0
    assert m.get("storage_version_id") >= 5
    assert m.get("storage_pinned_versions") == 0
    assert m.get("storage_bloom_filter_total", result="hit") >= 1

    text = m.render_prometheus()
    for name in (
        'storage_compaction_tasks_total{level="0"}',
        "storage_compaction_bytes_total",
        "storage_gc_objects_total",
        "storage_write_stall_seconds_total",
        "storage_l0_runs",
        "storage_sst_files",
    ):
        assert name in text, name


def test_serving_metrics_exported(tmp_path):
    """Serving-tier observability (ISSUE 5 satellite): pinned epoch,
    block-cache hit/miss/bytes, read counters and the per-read latency
    histogram flow out the replica's Prometheus exporter."""
    from risingwave_tpu.serve import ServingWorker

    eng = Engine(PlannerConfig(chunk_capacity=64,
                               agg_table_size=256,
                               agg_emit_capacity=64,
                               mv_table_size=256),
                 data_dir=str(tmp_path))
    eng.execute(
        "CREATE SOURCE t (k BIGINT, v BIGINT) "
        "WITH (connector='datagen');"
        "CREATE MATERIALIZED VIEW sm AS "
        "SELECT k % 4 AS g, count(*) AS n FROM t GROUP BY k % 4"
    )
    eng.tick(barriers=2, chunks_per_barrier=1)
    eng.storage_export_mv("sm")

    sv = ServingWorker(None, str(tmp_path)).start()
    try:
        for _ in range(3):
            cols, rows, epoch = sv.read("SELECT g, n FROM sm")
            assert len(rows) == 4 and epoch > 0
        sv.read("SELECT g, n FROM sm WHERE g = 1")
        m = sv.metrics
        assert m.get("serving_reads_total") == 4
        # the repeat scans HIT the result cache (same sql, same vid)
        assert m.get("serving_result_cache_hits") >= 2
        assert m.get("serving_result_cache_misses") >= 1
        assert m.get("serving_result_cache_bytes") > 0
        assert m.get("serving_result_cache_entries") >= 1
        assert 0.0 < m.get("serving_result_cache_hit_ratio") <= 1.0
        assert m.get("serving_pinned_epoch") > 0
        assert m.get("serving_block_cache_hits") >= 1
        assert m.get("serving_block_cache_misses") >= 1
        assert m.get("serving_block_cache_fill_bytes") > 0
        assert 0.0 < m.get("serving_block_cache_hit_ratio") <= 1.0
        assert m.get("serving_bloom_filter_total", result="hit") >= 1
        assert m.quantile("serving_read_seconds", 0.5) < float("inf")

        text = m.render_prometheus()
        for name in (
            "serving_reads_total",
            "serving_pinned_epoch",
            "serving_block_cache_hit_ratio",
            "serving_block_cache_fill_bytes",
            "serving_read_seconds_count",
            "serving_result_cache_hit_ratio",
            "serving_result_cache_bytes",
        ):
            assert name in text, name
        # error counter absent until an error actually happens
        assert sv.read_errors == 0
    finally:
        sv.stop()


def test_pushdown_metrics_exported(tmp_path):
    """Pushdown-plane observability (ISSUE 18 satellite): the elision
    counter is labeled by WHERE the work happened (compactor-side TTL
    drops vs replica-side block-walk filtering), block skips count,
    and the negative cache exports hit/entry gauges."""
    from risingwave_tpu.serve import ServingWorker

    eng = Engine(PlannerConfig(chunk_capacity=64, agg_table_size=256,
                               agg_emit_capacity=64, mv_table_size=256),
                 data_dir=str(tmp_path))
    eng.execute("CREATE TABLE e (seq BIGINT, v BIGINT, "
                "PRIMARY KEY (seq)) WITH (retract='true')")
    eng.execute("CREATE MATERIALIZED VIEW pe WITH (ttl = '10') AS "
                "SELECT seq, v FROM e")
    eng.execute("INSERT INTO e VALUES " +
                ", ".join(f"({i}, {i * 3})" for i in range(10)))
    eng.execute("FLUSH")
    eng.storage_export_mv("pe")
    # second cycle advances the horizon to 19: what the FIRST export
    # wrote below it is now the compactor's to drop
    eng.execute("INSERT INTO e VALUES " +
                ", ".join(f"({i}, {i * 3})" for i in range(10, 30)))
    eng.execute("FLUSH")
    eng.storage_export_mv("pe")
    eng.hummock.l0_trigger = 1
    while eng.hummock.compact_once():
        pass
    m = eng.metrics
    assert m.get("pushdown_rows_elided_total", where="compactor") > 0
    assert 'pushdown_rows_elided_total{where="compactor"}' \
        in m.render_prometheus()

    sv = ServingWorker(None, str(tmp_path)).start()
    try:
        # residual (non-pk) predicate: the block-walk evaluator runs
        # replica-side and counts the rows the client never saw
        _, rows, _ = sv.read("SELECT seq, v FROM pe WHERE v >= 66")
        assert rows and all(r[1] >= 66 for r in rows)
        sm = sv.metrics
        assert sm.get("pushdown_rows_elided_total", where="replica") > 0
        assert sm.get("pushdown_blocks_skipped_total") >= 0
        # missing-pk probes populate, then hit, the negative cache
        sv.multi_get("pe", [[990], [991]], cols=["seq", "v"])
        sv.multi_get("pe", [[990], [991]], cols=["seq", "v"])
        assert sm.get("serving_negative_cache_hits") >= 1
        assert sm.get("serving_negative_cache_entries") >= 1
        text = sm.render_prometheus()
        for name in (
            'pushdown_rows_elided_total{where="replica"}',
            "pushdown_blocks_skipped_total",
            "serving_negative_cache_hits",
            "serving_negative_cache_entries",
        ):
            assert name in text, name
    finally:
        sv.stop()


def test_single_node_orderly_stop_commits(tmp_path):
    """ISSUE 3 satellite: SingleNode.stop() seals + commits a final
    barrier — progress made since the last checkpoint survives a clean
    exit instead of being replayed-or-lost."""
    from risingwave_tpu.server import SingleNode

    cfg = PlannerConfig(chunk_capacity=64, agg_table_size=256,
                        agg_emit_capacity=64, mv_table_size=256)
    n = SingleNode(cfg, data_dir=str(tmp_path))
    n.engine.execute(
        "CREATE SOURCE t (k BIGINT) WITH (connector='datagen');"
        "CREATE MATERIALIZED VIEW m AS SELECT count(*) AS c FROM t"
    )
    n.tick(barriers=1, chunks_per_barrier=1)     # committed: 64 rows
    n.engine.jobs[0].run_chunk()                 # past the checkpoint
    n.stop()                                     # must commit 128

    eng2 = Engine(cfg, data_dir=str(tmp_path))
    assert eng2.execute("SELECT c FROM m") == [(128,)]


def test_cluster_metrics_exported(tmp_path):
    """ISSUE 3 satellite: control-plane observability — per-worker
    heartbeat age, live worker count, in-flight vs committed cluster
    epoch, barrier commit latency, failovers total — through the meta
    registry and the Prometheus exporter."""
    import time

    from risingwave_tpu.cluster import ComputeWorker, MetaService
    from risingwave_tpu.common.config import RwConfig

    cfg = RwConfig.from_dict({
        "streaming": {"chunk_size": 64},
        "state": {"agg_table_size": 256, "agg_emit_capacity": 64,
                  "mv_table_size": 256, "mv_ring_size": 512},
    })
    meta = MetaService(str(tmp_path), heartbeat_timeout_s=0.8)
    meta.start(port=0, monitor=False)
    w = ComputeWorker(f"127.0.0.1:{meta.rpc_port}", str(tmp_path),
                      config=cfg, heartbeat_interval_s=0.2).start()
    try:
        meta.execute_ddl(
            "CREATE SOURCE t (k BIGINT) WITH (connector='datagen');"
        )
        meta.execute_ddl(
            "CREATE MATERIALIZED VIEW cm AS "
            "SELECT k % 2 AS b, count(*) AS n FROM t GROUP BY k % 2"
        )
        for _ in range(2):
            assert meta.tick(1)["committed"]
        meta.check_heartbeats()

        m = meta.metrics
        assert m.get("cluster_live_workers") == 1
        assert m.get("cluster_jobs") == 1
        assert m.get("cluster_epoch_in_flight") == 2
        assert m.get("cluster_epoch_committed") == 2
        assert m.get("cluster_manifest_epoch") > 0
        age = m.get("cluster_worker_heartbeat_age_seconds",
                    worker=str(w.worker_id))
        assert 0.0 <= age < 0.8
        assert m.quantile("cluster_barrier_commit_seconds", 0.5) \
            < float("inf")

        # kill the worker silently: failover counter fires, its
        # heartbeat-age series is retired, live count drops to 0
        w.stop()
        deadline = time.monotonic() + 10
        while meta.failovers == 0:
            assert time.monotonic() < deadline
            time.sleep(0.1)
            meta.check_heartbeats()
        assert m.get("cluster_failovers_total") == 1
        assert m.get("cluster_live_workers") == 0
        with pytest.raises(KeyError):
            m.get("cluster_worker_heartbeat_age_seconds",
                  worker=str(w.worker_id))

        text = m.render_prometheus()
        for name in (
            "cluster_live_workers",
            "cluster_jobs",
            "cluster_epoch_in_flight",
            "cluster_epoch_committed",
            "cluster_manifest_epoch",
            "cluster_failovers_total",
            "cluster_barrier_commit_seconds_count",
        ):
            assert name in text, name
    finally:
        w.stop()
        meta.stop()


def test_worker_removal_retires_per_worker_series(tmp_path):
    """ISSUE 7 satellite: after a worker is REMOVED — scale-in
    deregistration or death — every one of its per-worker labeled
    series (heartbeat age, vnode count) leaves the scrape surface
    instead of lingering forever."""
    import time

    from risingwave_tpu.cluster import ComputeWorker, MetaService

    meta = MetaService(str(tmp_path), heartbeat_timeout_s=0.8,
                       scale_partitioning=True, n_vnodes=16)
    meta.start(port=0, monitor=False)
    addr = f"127.0.0.1:{meta.rpc_port}"
    w1 = ComputeWorker(addr, str(tmp_path),
                       heartbeat_interval_s=0.2).start()
    w2 = ComputeWorker(addr, str(tmp_path),
                       heartbeat_interval_s=0.2).start()
    try:
        meta.scale(2)  # cuts the map: per-worker vnode gauges exist
        meta.check_heartbeats()
        m = meta.metrics
        for w in (w1, w2):
            assert m.get("cluster_worker_vnodes",
                         worker=str(w.worker_id)) == 8
            assert m.get("cluster_worker_heartbeat_age_seconds",
                         worker=str(w.worker_id)) >= 0.0

        # graceful deregistration (the scale-in decommission path);
        # the process stops FIRST — a live worker would re-register
        # through its heartbeat loop, which is exactly the point of
        # that loop
        w2.stop()
        meta.rpc_unregister_worker(w2.worker_id)
        text = m.render_prometheus()
        assert f'worker="{w2.worker_id}"' not in text
        assert f'worker="{w1.worker_id}"' in text
        for name in ("cluster_worker_heartbeat_age_seconds",
                     "cluster_worker_vnodes"):
            with pytest.raises(KeyError):
                m.get(name, worker=str(w2.worker_id))
        assert w2.worker_id not in meta.workers  # fully removed

        # death path retires the same series
        w1.stop()
        deadline = time.monotonic() + 10
        while meta.metrics.get("cluster_live_workers") > 0:
            assert time.monotonic() < deadline
            time.sleep(0.1)
            meta.check_heartbeats()
        assert f'worker="{w1.worker_id}"' \
            not in m.render_prometheus()
    finally:
        w1.stop()
        w2.stop()
        meta.stop()


def test_fault_and_retry_gauges_exported(tmp_path):
    """ISSUE 6 satellite: the chaos fabric's injected counters and the
    unified RetryPolicy's budget spend are first-class metrics — per-op
    retry counters plus process gauges on the meta's scrape surface
    (the ``ctl cluster faults`` backing data)."""
    from risingwave_tpu.cluster import MetaService
    from risingwave_tpu.common import faults as faults_mod
    from risingwave_tpu.common.faults import (
        FaultFabric,
        FaultInjected,
        RetryPolicy,
    )

    meta = MetaService(str(tmp_path))
    fab = faults_mod.install(FaultFabric(seed=3))
    try:
        fab.fail_rpc(substr="a>b/", mode="drop", times=2)
        for _ in range(2):
            with pytest.raises(FaultInjected):
                fab.rpc_before_send("a>b/barrier")

        # spend the meta's retry budget against a dead endpoint
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("transient")
            return "ok"

        meta.retry.sleeper = lambda _: None
        assert meta.retry.run(flaky, label="barrier") == "ok"

        fl = meta.cluster_faults()
        assert fl["meta"]["fabric"]["injected_total"] == 2
        assert fl["meta"]["rpc_retries_total"] == 2

        m = meta.metrics
        assert m.get("faults_injected_total") == 2
        assert m.get("rpc_retries_spent_total") == 2
        assert m.get("rpc_retry_gave_up_spent_total") == 0
        assert m.get("rpc_retries_total", op="barrier") == 2
        text = m.render_prometheus()
        for name in ("faults_injected_total",
                     "rpc_retries_spent_total",
                     "rpc_retry_gave_up_spent_total",
                     "rpc_retries_total"):
            assert name in text, name

        # a per-policy budget exhaustion lands on the gave-up counter
        p = RetryPolicy(max_attempts=2, base_delay_s=0.001,
                        metrics=m, sleeper=lambda _: None)

        def dead():
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            p.run(dead, label="upload")
        assert m.get("rpc_retry_gave_up_total", op="upload") == 1
    finally:
        faults_mod.install(None)


def test_meta_store_crash_safe_append_and_torn_tail(tmp_path):
    """ISSUE 3 satellite: a worker killed mid-append leaves a torn
    trailing JSONL line — replay drops it (with a warning) instead of
    poisoning recovery; damage anywhere else stays loud."""
    import pytest as _pytest

    from risingwave_tpu.meta.store import MetaStore, MetaStoreCorruption

    store = MetaStore(str(tmp_path))
    store.append_ddl("CREATE TABLE a (x BIGINT)")
    store.append_ddl("CREATE TABLE b (x BIGINT)")
    path = store._ddl_path
    # crash mid-append: truncated JSON, no trailing newline
    with open(path, "a") as f:
        f.write('{"sql": "CREATE TAB')
    assert store.ddl_log() == [
        "CREATE TABLE a (x BIGINT)", "CREATE TABLE b (x BIGINT)",
    ]
    # appending after recovery overwrites nothing and replays cleanly
    # (the torn bytes stay, but the reader stops at them — matching
    # the write path, which only ever appends)
    with open(path) as f:
        lines = f.read().splitlines()
    assert len(lines) == 3

    # a valid-JSON line missing its newline was also never acked
    store2 = MetaStore(str(tmp_path / "t2"))
    store2.append_ddl("CREATE TABLE c (x BIGINT)")
    with open(store2._ddl_path, "a") as f:
        f.write('{"sql": "SET x = 1"}')  # no \n: fsync never covered it
    assert store2.ddl_log() == ["CREATE TABLE c (x BIGINT)"]

    # corruption MID-log (not a crash artifact) must raise, not
    # silently truncate acknowledged history
    store3 = MetaStore(str(tmp_path / "t3"))
    store3.append_ddl("CREATE TABLE d (x BIGINT)")
    store3.append_ddl("CREATE TABLE e (x BIGINT)")
    with open(store3._ddl_path) as f:
        content = f.read()
    with open(store3._ddl_path, "w") as f:
        f.write(content.replace('TABLE d', 'TAB"LE d', 1))
    with _pytest.raises(MetaStoreCorruption):
        store3.ddl_log()


def test_checkpoint_pipeline_metrics_exported(tmp_path):
    """ISSUE 4 satellite: checkpoint-pipeline observability — upload
    queue depth, sealed-vs-committed epoch lag, snapshot dirty-block
    ratio, and snapshot/upload seconds — through the engine registry
    and the Prometheus exporter."""
    eng = Engine(PlannerConfig(chunk_capacity=64, agg_table_size=256,
                               agg_emit_capacity=64, mv_table_size=256),
                 data_dir=str(tmp_path))
    eng.execute(
        "CREATE SOURCE t (k BIGINT) WITH (connector='datagen');"
        "CREATE MATERIALIZED VIEW m AS "
        "SELECT k % 2 AS b, count(*) AS n FROM t GROUP BY k % 2"
    )
    eng.tick(barriers=3, chunks_per_barrier=1)
    eng.collect_checkpoint_metrics()
    m = eng.metrics
    job = eng.jobs[0].name
    assert m.get("sealed_epoch", job=job) > 0
    assert m.get("sealed_epoch", job=job) \
        == m.get("committed_epoch", job=job)
    # tick() drains at the batch boundary: lag and queue are 0
    assert m.get("checkpoint_seal_lag_epochs", job=job) == 0
    assert m.get("checkpoint_upload_queue_depth", job=job) == 0
    assert m.get("checkpoint_uploads_total", job=job) >= 3
    assert m.get("checkpoint_upload_seconds_total", job=job) > 0
    ratio = m.get("snapshot_dirty_block_ratio", job=job)
    assert 0.0 <= ratio <= 1.0
    assert m.get("snapshot_shadow_blocks", job=job) > 0
    # histogram from the uploader thread
    assert m.quantile("checkpoint_upload_seconds", 0.5, job=job) \
        < float("inf")

    text = m.render_prometheus()
    for name in (
        "sealed_epoch",
        "checkpoint_seal_lag_epochs",
        "checkpoint_upload_queue_depth",
        "checkpoint_uploads_total",
        "checkpoint_upload_seconds_total",
        "snapshot_dirty_block_ratio",
        "snapshot_shadow_blocks",
        "checkpoint_upload_seconds_count",
    ):
        assert name in text, name

    # steady-state durable epochs persist as deltas (the shared-digest
    # incremental path is live end-to-end)
    store = eng.checkpoint_store
    kinds = [store.checkpoint_kind(job, e) for e in store.epochs(job)]
    assert "delta" in kinds, kinds


def test_join_path_metrics_exported():
    """ISSUE 2 satellite: the join path exports probes-per-chunk, pool
    occupancy, emission-window fill, and drain-loop gauges through the
    Prometheus registry (Engine.collect_join_metrics +
    audit_join_probe_counts)."""
    eng = Engine(PlannerConfig(
        chunk_capacity=128,
        join_left_table_size=1 << 10, join_right_table_size=1 << 10,
        join_pool_size=1 << 12, join_out_capacity=128,
        mv_table_size=1 << 10, mv_ring_size=1 << 12,
    ))
    eng.execute("""
    CREATE SOURCE person (
        id BIGINT, name VARCHAR, date_time TIMESTAMP,
        WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
    ) WITH (connector = 'nexmark', nexmark.table = 'person',
            nexmark.event.rate = '1000000');
    CREATE SOURCE auction (
        id BIGINT, seller BIGINT, reserve BIGINT, expires TIMESTAMP,
        date_time TIMESTAMP,
        WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
    ) WITH (connector = 'nexmark', nexmark.table = 'auction',
            nexmark.event.rate = '1000000');
    CREATE MATERIALIZED VIEW jm AS
    SELECT p.id AS id, a.reserve AS reserve
    FROM TUMBLE(person, date_time, INTERVAL '1' SECOND) p
    JOIN TUMBLE(auction, date_time, INTERVAL '1' SECOND) a
    ON p.id = a.seller AND p.window_start = a.window_start;
    """)
    eng.tick(barriers=2, chunks_per_barrier=1)

    # trace-time audit: the fused (hash, rank) update compiles exactly
    # ONE lookup_or_insert per append-only side (acceptance criterion)
    audit = eng.audit_join_probe_counts()
    assert audit, "q8-shaped plan should have pool join sides"
    for stats in audit.values():
        assert stats == {"lookup": 0, "lookup_or_insert": 1}

    eng.collect_join_metrics()
    m = eng.metrics
    text = m.render_prometheus()
    for name in (
        "join_probe_calls_per_chunk",
        "join_probe_iters_per_chunk",
        "join_pool_occupancy",
        "join_emit_window_fill_ratio",
        "join_drain_windows_per_chunk",
    ):
        assert name in text, name
    # both pool sides occupy some of their pools after two barriers
    job = eng.jobs[0].name
    from risingwave_tpu.stream.dag import JoinNode
    jidx = next(i for i, n in enumerate(eng.jobs[0].nodes)
                if isinstance(n, JoinNode))
    for side in ("left", "right"):
        occ = m.get("join_pool_occupancy", job=job, node=str(jidx),
                    side=side)
        assert 0.0 < occ <= 1.0


def test_integrity_and_scrub_metrics_exported(tmp_path):
    """Integrity satellite: the full metric surface — typed error
    counters, quarantine gauge, scrub progress gauges, repair
    counters — lands on the Prometheus scrape surface."""
    import os

    from risingwave_tpu.storage.checkpoint_store import CheckpointStore
    from risingwave_tpu.storage.hummock import (
        HummockStorage,
        LocalFsObjectStore,
    )
    from risingwave_tpu.storage.hummock.scrubber import ScrubberService

    m = MetricsRegistry()
    storage = HummockStorage(
        LocalFsObjectStore(str(tmp_path / "hummock")), metrics=m)
    keys = [f"k{i:04d}".encode() for i in range(200)]
    storage.write_batch([(k, b"v" + k) for k in keys], epoch=1)

    # the meta's wiring, in miniature: scrub detection -> typed
    # counter + durable quarantine note
    def on_corruption(kind, key, _ctx):
        m.inc("integrity_errors_total", kind=kind)
        storage.quarantine_sst(key, "scrub mismatch")

    scrub = ScrubberService(storage, metrics=m, pace_s=0.0,
                            on_corruption=on_corruption)
    assert scrub.run_once()["corrupt"] == []

    sst_key = next(iter(storage.versions.current.all_keys()))
    path = os.path.join(str(tmp_path / "hummock"), sst_key)
    with open(path, "r+b") as f:
        f.seek(40)
        f.write(b"\x99")
    assert scrub.run_once()["corrupt"] == [("sst", sst_key)]

    # checkpoint corruption + self-healing rewind (repair counter)
    ck = CheckpointStore(str(tmp_path / "ck"), keep_epochs=8,
                         metrics=m)
    for e in (1, 2):
        ck.save("j", e, {"a": np.arange(32, dtype=np.int64)},
                {"offset": e})
    with open(os.path.join(str(tmp_path / "ck"), "j",
                           "epoch_2.npz"), "r+b") as f:
        f.seek(10)
        f.write(b"\x77")
    assert ck.load("j")[0] == 1  # healed back to the verified epoch

    rendered = m.render_prometheus()
    assert 'integrity_errors_total{kind="sst"}' in rendered
    assert 'integrity_errors_total{kind="checkpoint"}' in rendered
    assert 'integrity_repairs_total{kind="checkpoint_rewind"}' \
        in rendered
    assert "quarantined_objects" in rendered
    assert m.get("quarantined_objects") >= 1
    assert "scrub_objects_verified_total" in rendered
    assert m.get("scrub_objects_verified_total") >= 1
    assert "scrub_cursor_age_s" in rendered
    assert 'scrub_corruptions_total{kind="sst"}' in rendered
    assert "scrub_cycles_total" in rendered


def test_dag_fused_fallback_counter_exported():
    """ISSUE 9 satellite: a DagJob window that cannot run as ONE fused
    dispatch (host-chunk DML sources here) is counted by reason and
    exported as ``dag_fused_fallback_total{reason}`` — the silent
    per-chunk degradation becomes observable."""
    eng = Engine(PlannerConfig(
        chunk_capacity=64,
        join_table_size=512, join_bucket_cap=16,
        join_out_capacity=1 << 10,
        mv_table_size=512, mv_ring_size=1 << 12,
    ))
    eng.execute("CREATE TABLE lt (k BIGINT, v BIGINT)")
    eng.execute("CREATE TABLE rt (k BIGINT, w BIGINT)")
    eng.execute("INSERT INTO lt VALUES (1, 10), (2, 20)")
    eng.execute("INSERT INTO rt VALUES (1, 100), (2, 200)")
    eng.execute(
        "CREATE MATERIALIZED VIEW jm AS SELECT lt.k AS k, lt.v AS v, "
        "rt.w AS w FROM lt JOIN rt ON lt.k = rt.k"
    )
    eng.tick(barriers=1, chunks_per_barrier=4)
    job = eng.jobs[0]
    assert job.fused_fallbacks.get("host_chunk_source", 0) >= 1
    eng.collect_join_metrics()
    got = eng.metrics.get("dag_fused_fallback_total", job=job.name,
                          reason="host_chunk_source")
    assert got >= 1
    assert "dag_fused_fallback_total" in eng.metrics.render_prometheus()


def test_exchange_metrics_exported_and_retired(tmp_path):
    """Exchange-lite satellite: the sliced peer exchange exports
    per-EDGE counters (rows/bytes/batches) plus a per-batch latency
    histogram on the sending worker, and the meta mirrors per-worker
    exchange gauges that are RETIRED with the worker — exactly the
    PR-7/PR-10 per-peer series discipline."""
    from risingwave_tpu.cluster import ComputeWorker, MetaService

    meta = MetaService(str(tmp_path), heartbeat_timeout_s=60.0,
                       scale_partitioning=True, n_vnodes=16)
    meta.start(port=0, monitor=False)
    addr = f"127.0.0.1:{meta.rpc_port}"
    w1 = ComputeWorker(addr, str(tmp_path),
                       heartbeat_interval_s=5.0).start()
    w2 = ComputeWorker(addr, str(tmp_path),
                       heartbeat_interval_s=5.0).start()
    try:
        meta.scale(2)
        meta.execute_ddl("CREATE TABLE t (k BIGINT, v BIGINT)")
        meta.execute_ddl(
            "CREATE MATERIALIZED VIEW agg AS "
            "SELECT k, count(*) AS n FROM t GROUP BY k"
        )
        # the compiled choreography marks the table shuffled
        ex = meta.state()["exchange"]
        assert ex["tables"]["t"]["mode"] == "shuffle"
        assert ex["tables"]["t"]["key_col"] == 0
        assert any(s["edge"] == "src:t>agg" for s in ex["specs"])
        vals = ",".join(f"({i % 7},{i})" for i in range(64))
        meta.execute_ddl(f"INSERT INTO t VALUES {vals}")
        for _ in range(3):
            assert meta.tick(1)["committed"]

        # per-edge counters + latency histogram on the SENDING worker
        leader = w1 if "agg" in {j.name for j in w1.engine.jobs} \
            and w1.worker_id == min(w1.worker_id, w2.worker_id) \
            else w2
        text = leader.engine.metrics.render_prometheus()
        assert 'cluster_exchange_rows_total{edge="src:t>agg"}' in text
        assert 'cluster_exchange_bytes_total{edge="src:t>agg"}' in text
        assert 'cluster_exchange_batches_total{edge="src:t>agg"}' \
            in text
        assert 'cluster_exchange_batch_seconds_count' \
            '{edge="src:t>agg"}' in text
        assert leader.rpc_metrics()["prometheus"] == text

        # meta-side per-worker mirrors exist for the leader...
        lead_id = str(leader.worker_id)
        assert meta.metrics.get("cluster_worker_exchange_rows_out",
                                worker=lead_id) > 0
        # ...and are RETIRED with the worker
        (dead := w2).stop()
        meta.rpc_unregister_worker(dead.worker_id)
        text = meta.metrics.render_prometheus()
        assert f'worker="{dead.worker_id}"' not in text
    finally:
        w1.stop()
        w2.stop()
        meta.stop()


def test_workload_txn_metrics_exported():
    """ISSUE 16 satellite: the CH driver's per-transaction families —
    ``workload_txn_total{type}``, ``workload_txn_rows_total`` and the
    wide-grid ``workload_txn_seconds{type}`` histogram — land on the
    registry in exportable shape (one series per transaction type,
    bucket bounds past the default 10s grid)."""
    from risingwave_tpu.common.metrics import MetricsRegistry
    from risingwave_tpu.workload.driver import observe_txn

    m = MetricsRegistry()
    observe_txn("new_order", 0.05, 12, metrics=m)
    observe_txn("new_order", 42.0, 9, metrics=m)
    observe_txn("payment", 0.02, 6, metrics=m)
    observe_txn("delivery", 0.3, 15, metrics=m)

    assert m.get("workload_txn_total", type="new_order") == 2
    assert m.get("workload_txn_total", type="payment") == 1
    assert m.get("workload_txn_total", type="delivery") == 1
    assert m.get("workload_txn_rows_total") == 42

    text = m.render_prometheus()
    assert '# TYPE workload_txn_seconds histogram' in text
    for kind in ("new_order", "payment", "delivery"):
        assert f'workload_txn_seconds_count{{type="{kind}"}} ' in text
    # the wide grid keeps a 42s txn out of the +Inf bucket
    assert 'le="60"' in text
    assert m.quantile("workload_txn_seconds", 0.99,
                      type="new_order") == 60.0
