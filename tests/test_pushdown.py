"""Pushdown plane (ISSUE 18): compaction-time expiry policies, the
manifest ride-along, the bottommost-only legality gate, and the
UPDATE / WITH (ttl = ...) SQL surface.

Ref: RocksDB's compaction_filter + TTL compactions, and RisingWave's
state-cleaning watermark on storage (risingwave state_cleaning):
expiry is EVENTUAL — rows below the horizon stop being exported and
the bottommost compaction drops them; nothing is ever dropped above
deeper data (that would resurrect the older value underneath).
"""

import struct

import pytest

from risingwave_tpu.common.metrics import MetricsRegistry
from risingwave_tpu.storage.hummock import (
    HummockStorage,
    InMemObjectStore,
    LocalFsObjectStore,
)
from risingwave_tpu.storage.pushdown import (
    ExpiryPolicy,
    PolicySet,
    merge_policy_docs,
    partition_elidable,
    table_prefix,
)


def _mc(v: int) -> bytes:
    """int64 memcomparable (sign-flip offset binary), non-negative."""
    return struct.pack(">Q", v ^ (1 << 63))


def _pol(table: str, horizon: int, ttl: int = 10,
         epoch: int = 1) -> ExpiryPolicy:
    pfx = table_prefix(table)
    return ExpiryPolicy(table=table, prefix=pfx,
                        expire_below=pfx + _mc(horizon),
                        horizon=horizon, ttl=ttl, column="seq",
                        epoch=epoch)


def _key(table: str, seq: int) -> bytes:
    return table_prefix(table) + _mc(seq)


# -- policy docs (unit) --------------------------------------------------
def test_policy_doc_roundtrip_and_merge():
    p = _pol("tt", 19, ttl=10, epoch=7)
    assert ExpiryPolicy.from_doc(p.to_doc()) == p
    ps = PolicySet.from_docs({"tt": p.to_doc()})
    # expired iff prefix <= key < expire_below — pure byte compares
    assert ps.expired(_key("tt", 18))
    assert not ps.expired(_key("tt", 19))
    assert not ps.expired(_key("other", 0))
    assert ps.get("tt").horizon == 19 and ps.get("nope") is None
    # newest-epoch-wins per table; None removes (DROP)
    older, newer = _pol("tt", 5, epoch=3), _pol("tt", 30, epoch=9)
    docs = merge_policy_docs({"tt": newer.to_doc()},
                             {"tt": older.to_doc()})
    assert docs["tt"]["horizon"] == 30
    docs = merge_policy_docs(docs, {"tt": None})
    assert docs == {}


# -- compaction filter: drop + manifest ride-along + restart -------------
def test_compaction_filter_expiry_never_resurrects(tmp_path):
    """Expired rows (and whole dead tombstone ranges) drop at the
    bottommost compaction, the policy survives a storage restart via
    the manifest, and NO later compaction or diff brings them back."""
    store = LocalFsObjectStore(str(tmp_path / "os"))
    st = HummockStorage(store, metrics=MetricsRegistry(),
                        l0_trigger=2, base_bytes=1 << 16, ratio=4,
                        stall_l0=64)
    # three generations: values, overwrites, a dead tombstone range
    st.write_batch([(_key("tt", s), b"old") for s in range(40)],
                   epoch=1)
    st.write_batch([(_key("tt", s), b"new") for s in range(20, 60)],
                   epoch=2)
    st.delete_batch([_key("tt", s) for s in range(10, 16)], epoch=3)
    st.set_policy("tt", _pol("tt", 30, epoch=3).to_doc())

    # RESTART before compacting: the policy rides the manifest, so a
    # fresh compactor process enforces the same horizon
    st.close()
    st2 = HummockStorage(store, metrics=MetricsRegistry(),
                         l0_trigger=2, base_bytes=1 << 16, ratio=4,
                         stall_l0=64)
    assert st2.policy_set().get("tt").horizon == 30
    while st2.compact_once():
        pass
    assert st2.pushdown_rows_elided > 0
    got = dict(st2.scan())
    assert set(got) == {_key("tt", s) for s in range(30, 60)}
    # rows the horizon spared keep their newest value byte-for-byte
    assert got[_key("tt", 30)] == b"new"

    # further churn + compaction: nothing below 30 ever reappears
    st2.write_batch([(_key("tt", s), b"v3") for s in range(55, 70)],
                    epoch=4)
    st2.write_batch([(_key("tt", 70), b"v3")], epoch=5)
    while st2.compact_once():
        pass
    assert all(k >= _key("tt", 30) for k in dict(st2.scan()))
    st2.close()


def test_expiry_only_drops_at_bottommost(tmp_path):
    """The legality gate: a compaction whose output sits ABOVE deeper
    data must NOT apply the filter (dropping there would resurrect
    the older value underneath); once the merge reaches the bottom,
    the expired keys go."""
    st = HummockStorage(InMemObjectStore(), l0_trigger=2,
                        base_bytes=512, ratio=2, stall_l0=64)
    # push data down to L2: tiny level budgets force cascading
    for e in range(1, 7):
        st.write_batch([(_key("tt", s), f"e{e}".encode())
                        for s in range(64)], epoch=e)
        while st.compact_once():
            pass
    v = st.versions.current
    assert any(len(lv) for lv in v.levels[2:]), \
        "setup failed to fill a deeper level"
    st.set_policy("tt", _pol("tt", 32, epoch=7).to_doc())
    # fresh L0 runs on top; the first task's output is NOT bottommost
    st.write_batch([(_key("tt", 0), b"x")], epoch=7)
    st.write_batch([(_key("tt", 1), b"x")], epoch=8)
    task = st.pick_compaction()
    assert task is not None and task.in_level == 0
    assert not task.drop_tombstones
    assert task.policies is None  # the gate under test
    st.execute_compaction(task)
    st.commit_compaction(task)
    # the non-bottommost pass dropped NOTHING: expired keys survive
    # above the deeper data (no mid-level resurrection hazard)
    assert st.pushdown_rows_elided == 0
    assert st.get(_key("tt", 0)) == b"x"
    # squeeze the levels until the merge reaches the bottom: the
    # bottommost pass (and only it) enforces the horizon
    st.base_bytes = 1
    for _ in range(32):
        if all(k >= _key("tt", 32) for k in dict(st.scan())):
            break
        if not st.compact_once():
            break
    assert all(k >= _key("tt", 32) for k in dict(st.scan()))
    assert st.pushdown_rows_elided > 0


def test_whole_sst_elision_counts_without_reads():
    """An input SST entirely below the horizon is elided outright —
    no block read; manifest row counts account for it."""
    st = HummockStorage(InMemObjectStore(), l0_trigger=2,
                        base_bytes=1 << 16, ratio=4, stall_l0=64)
    st.write_batch([(_key("tt", s), b"a") for s in range(20)], epoch=1)
    st.write_batch([(_key("tt", s), b"b") for s in range(100, 120)],
                   epoch=2)
    pol = _pol("tt", 50, epoch=2)
    dead, live = partition_elidable(
        st.versions.current.levels[0],
        PolicySet.from_docs({"tt": pol.to_doc()}),
    )
    assert len(dead) == 1 and len(live) == 1
    assert sum(s.n_records for s in dead) == 20
    st.set_policy("tt", pol.to_doc())
    while st.compact_once():
        pass
    assert st.pushdown_ssts_elided == 1
    assert st.pushdown_rows_elided == 20
    assert set(dict(st.scan())) == {_key("tt", s)
                                    for s in range(100, 120)}


# -- SQL surface: UPDATE sugar + WITH (ttl = ...) ------------------------
def _engine(tmp_path):
    from risingwave_tpu.sql import Engine
    from risingwave_tpu.sql.planner import PlannerConfig

    return Engine(PlannerConfig(
        chunk_capacity=64, agg_table_size=256, agg_emit_capacity=64,
        mv_table_size=256, mv_ring_size=1024,
    ), data_dir=str(tmp_path / "data"))


def test_update_sugar_desugars_to_retraction_pair(tmp_path):
    from risingwave_tpu.sql import ast
    from risingwave_tpu.sql.parser import parse

    (stmt,) = parse("UPDATE w SET ytd = 5, tax = 2 WHERE w_id = 1")
    assert isinstance(stmt, ast.Update) and stmt.table == "w"
    assert [c for c, _ in stmt.assignments] == ["ytd", "tax"]

    eng = _engine(tmp_path)
    eng.execute("CREATE TABLE w (w_id BIGINT, name VARCHAR(16), "
                "ytd BIGINT, PRIMARY KEY (w_id)) "
                "WITH (retract='true')")
    eng.execute("INSERT INTO w VALUES (1, 'a', 100), (2, 'b', 200)")
    eng.execute("CREATE MATERIALIZED VIEW mw AS "
                "SELECT w_id, ytd FROM w")
    eng.execute("FLUSH")
    eng.execute("UPDATE w SET ytd = 150 WHERE w_id = 1")
    eng.execute("UPDATE w SET ytd = 250 WHERE 2 = w_id")  # reversed
    eng.execute("FLUSH")
    assert sorted(eng.execute("SELECT * FROM mw")) \
        == [(1, 150), (2, 250)]
    # the sugar accepts ONLY the shapes the retraction pair can honor
    for bad, msg in [
        ("UPDATE w SET ytd = 1 WHERE name = 'a'", "full primary key"),
        ("UPDATE w SET w_id = 9 WHERE w_id = 1", "primary-key column"),
        ("UPDATE w SET ytd = 1 WHERE w_id = 99", "no live row"),
        ("UPDATE w SET ytd = 1, ytd = 2 WHERE w_id = 1", "twice"),
    ]:
        with pytest.raises(ValueError, match=msg):
            eng.execute(bad)
    # rows ride the DML journal: a cold restart replays the UPDATE
    del eng
    eng2 = _engine(tmp_path)
    assert sorted(eng2.execute("SELECT * FROM mw")) \
        == [(1, 150), (2, 250)]


def test_mv_ttl_option_validation(tmp_path):
    eng = _engine(tmp_path)
    eng.execute("CREATE TABLE t (k BIGINT, s VARCHAR(8), v BIGINT, "
                "PRIMARY KEY (k)) WITH (retract='true')")
    with pytest.raises(ValueError, match="ttl"):
        eng.execute("CREATE MATERIALIZED VIEW m1 WITH (nope = '1') "
                    "AS SELECT k, v FROM t")
    with pytest.raises(ValueError, match="positive"):
        eng.execute("CREATE MATERIALIZED VIEW m1 WITH (ttl = '0') "
                    "AS SELECT k, v FROM t")
    # leading export-pk must be a fixed-width orderable column — a
    # string horizon has no ttl arithmetic
    with pytest.raises(ValueError):
        eng.execute("CREATE MATERIALIZED VIEW m2 WITH (ttl = '5') "
                    "AS SELECT s, sum(v) AS sv FROM t GROUP BY s")
    eng.execute("CREATE MATERIALIZED VIEW m3 WITH (ttl = '5') "
                "AS SELECT k, v FROM t")
    assert eng.catalog.get("m3").ttl == ("k", 5)


def test_ttl_mv_expiry_end_to_end(tmp_path):
    """Eventual expiry through the export path: below-horizon keys
    get neither upserts nor tombstones, the compactor drops what
    earlier exports wrote (counter moves), later diffs cannot
    resurrect them, and DROP retires the policy from the manifest."""
    eng = _engine(tmp_path)
    eng.execute("CREATE TABLE e (seq BIGINT, v BIGINT, "
                "PRIMARY KEY (seq)) WITH (retract='true')")
    eng.execute("CREATE MATERIALIZED VIEW me WITH (ttl = '10') AS "
                "SELECT seq, v FROM e")
    eng.execute("INSERT INTO e VALUES " +
                ", ".join(f"({i}, {i})" for i in range(10)))
    eng.execute("FLUSH")
    eng.storage_export_mv("me")
    eng.execute("INSERT INTO e VALUES " +
                ", ".join(f"({i}, {i})" for i in range(10, 30)))
    eng.execute("FLUSH")
    eng.storage_export_mv("me")
    pol = eng.hummock.policy_set().get("me")
    assert pol is not None and pol.horizon == 19
    eng.hummock.l0_trigger = 1
    while eng.hummock.compact_once():
        pass
    assert eng.hummock.pushdown_rows_elided > 0
    served = sorted(int(r[0]) for r in eng.storage_serve_mv("me"))
    assert served == list(range(19, 30))
    # one more export cycle: the horizon advances with the new max
    # seq (30 - 10 = 20) and the already-expired keys stay gone
    eng.execute("INSERT INTO e VALUES (30, 30)")
    eng.execute("FLUSH")
    eng.storage_export_mv("me")
    assert eng.hummock.policy_set().get("me").horizon == 20
    while eng.hummock.compact_once():
        pass
    served = sorted(int(r[0]) for r in eng.storage_serve_mv("me"))
    assert served == list(range(20, 31))
    eng.execute("DROP MATERIALIZED VIEW me")
    assert eng.hummock.policy_set().get("me") is None
