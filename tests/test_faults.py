"""The deterministic fault fabric + unified retry policy (ISSUE 6):
seeded determinism, RPC transport injection, retry/backoff behavior,
and malformed-frame hardening of the JSON-RPC server."""

import json
import socket
import threading
import time

import pytest

from risingwave_tpu.common import faults as faults_mod
from risingwave_tpu.common.faults import (
    FaultFabric,
    FaultInjected,
    RetryPolicy,
    splitmix64,
)
from risingwave_tpu.cluster.rpc import RpcClient, RpcError, RpcServer


@pytest.fixture(autouse=True)
def _no_global_fabric():
    """Every test starts and ends with NO process-global fabric (a
    leaked fabric would inject into unrelated suites)."""
    faults_mod.install(None)
    yield
    faults_mod.install(None)


# -- determinism ---------------------------------------------------------
def test_storm_expansion_is_deterministic():
    a = FaultFabric.storm(42, op="rpc", n=16, span=100,
                          modes=("drop", "error_after_send"))
    b = FaultFabric.storm(42, op="rpc", n=16, span=100,
                          modes=("drop", "error_after_send"))
    assert a.to_json() == b.to_json()
    # a different seed yields a different schedule
    c = FaultFabric.storm(43, op="rpc", n=16, span=100,
                          modes=("drop", "error_after_send"))
    assert a.to_json() != c.to_json()


def test_identical_seed_identical_injection_sequence():
    """The acceptance criterion verbatim: drive the same op sequence
    through two fabrics built from the same seed — the injected-fault
    positions must match exactly (counter-addressed, no RNG)."""
    def drive(fab):
        hits = []
        for i in range(200):
            try:
                fab.rpc_before_send(f"meta>worker1/barrier#{i}")
            except FaultInjected:
                hits.append(i)
        return hits

    seq1 = drive(FaultFabric.storm(7, op="rpc", n=8, span=150))
    seq2 = drive(FaultFabric.storm(7, op="rpc", n=8, span=150))
    assert seq1 == seq2 and len(seq1) > 0


def test_retry_policy_jitter_is_deterministic():
    p1 = RetryPolicy(seed=5)
    p2 = RetryPolicy(seed=5)
    assert [p1.delay(a) for a in range(1, 8)] \
        == [p2.delay(a) for a in range(1, 8)]
    # capped: never above max_delay_s
    assert all(p1.delay(a) <= p1.max_delay_s for a in range(1, 20))
    # splitmix64 is a pure function
    assert splitmix64(123) == splitmix64(123)


# -- retry policy behavior ----------------------------------------------
def test_retry_policy_retries_then_succeeds():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("transient")
        return "ok"

    p = RetryPolicy(max_attempts=5, base_delay_s=0.001, sleeper=lambda _: None)
    assert p.run(flaky) == "ok"
    assert len(calls) == 3 and p.retries == 2 and p.gave_up == 0


def test_retry_policy_exhausts_budget_and_raises():
    from risingwave_tpu.common.metrics import MetricsRegistry

    m = MetricsRegistry()
    p = RetryPolicy(max_attempts=3, base_delay_s=0.001, metrics=m,
                    sleeper=lambda _: None)

    def dead():
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        p.run(dead, label="barrier")
    assert p.retries == 2 and p.gave_up == 1
    assert m.get("rpc_retries_total", op="barrier") == 2
    assert m.get("rpc_retry_gave_up_total", op="barrier") == 1


def test_retry_policy_never_retries_rpc_error():
    calls = []

    def refused():
        calls.append(1)
        raise RpcError("no")

    p = RetryPolicy(max_attempts=5, base_delay_s=0.001)
    with pytest.raises(RpcError):
        p.run(refused)
    assert len(calls) == 1  # RpcError is FINAL, never retried


# -- RPC transport injection --------------------------------------------
class _Counter:
    def __init__(self):
        self.calls = 0

    def rpc_bump(self):
        self.calls += 1
        return {"calls": self.calls}


def test_rpc_drop_and_delay_injection():
    target = _Counter()
    server = RpcServer(target).start()
    fab = faults_mod.install(FaultFabric(seed=1))
    fab.fail_rpc(substr="a>b/bump", after=1, mode="drop")
    # NB: a firing rule short-circuits later rules' counters for that
    # op, so this arms "the next matching op after the drop fires"
    fab.fail_rpc(substr="a>b/bump", after=1, mode="delay",
                 delay_s=0.2)
    try:
        c = RpcClient("127.0.0.1", server.port, timeout=5,
                      src="a", dst="b")
        assert c.call("bump")["calls"] == 1
        with pytest.raises(ConnectionError):
            c.call("bump")  # dropped before send
        assert target.calls == 1  # the peer never saw it
        t0 = time.monotonic()
        assert c.call("bump")["calls"] == 2  # delayed, not errored
        assert time.monotonic() - t0 >= 0.2
        assert fab.injected_total() == 1  # a delay is not an error
        assert fab.delays == 1
        c.close()
    finally:
        server.stop()


def test_rpc_error_after_send_executes_but_loses_response():
    target = _Counter()
    server = RpcServer(target).start()
    fab = faults_mod.install(FaultFabric(seed=1))
    fab.fail_rpc(substr="/bump", mode="error_after_send")
    try:
        c = RpcClient("127.0.0.1", server.port, timeout=5)
        with pytest.raises(ConnectionError, match="error-after-send"):
            c.call("bump")
        deadline = time.monotonic() + 5
        while target.calls == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert target.calls == 1  # delivered AND executed
        assert c.call("bump")["calls"] == 2  # client reconnects
        c.close()
    finally:
        server.stop()


def test_rpc_one_way_partition_and_heal():
    t1, t2 = _Counter(), _Counter()
    s1, s2 = RpcServer(t1).start(), RpcServer(t2).start()
    fab = faults_mod.install(FaultFabric())
    rule = fab.partition("meta", "w1")
    try:
        a_to_b = RpcClient("127.0.0.1", s1.port, timeout=5,
                           src="meta", dst="w1")
        b_to_a = RpcClient("127.0.0.1", s2.port, timeout=5,
                           src="w1", dst="meta")
        with pytest.raises(ConnectionError):
            a_to_b.call("bump")
        # one-way: the reverse direction flows
        assert b_to_a.call("bump")["calls"] == 1
        FaultFabric.heal(rule)
        assert a_to_b.call("bump")["calls"] == 1
        a_to_b.close()
        b_to_a.close()
    finally:
        s1.stop()
        s2.stop()


def test_env_var_boots_the_fabric(monkeypatch):
    spec = FaultFabric.storm(9, op="put", substr="epoch_", n=3)
    monkeypatch.setenv(faults_mod.ENV_VAR, json.dumps(spec.to_json()))
    faults_mod._ENV_CHECKED = False
    faults_mod._FABRIC = None
    fab = faults_mod.get_fabric()
    assert fab is not None and fab.seed == 9 and len(fab.rules) == 3
    assert fab.to_json() == spec.to_json()


# -- malformed / torn frames never crash the server ----------------------
def _raw_roundtrip(sock_file, payload: bytes) -> dict:
    sock_file.write(payload)
    sock_file.flush()
    return json.loads(sock_file.readline())


def test_malformed_frames_yield_rpc_error_not_crash():
    target = _Counter()
    server = RpcServer(target).start()
    try:
        s = socket.create_connection(("127.0.0.1", server.port),
                                     timeout=5)
        f = s.makefile("rwb")
        # junk bytes
        resp = _raw_roundtrip(f, b"\x00\xffnot json at all\n")
        assert "malformed" in resp["error"]
        # truncated JSON (torn frame, newline landed)
        resp = _raw_roundtrip(f, b'{"id": 1, "method": "bu\n')
        assert "malformed" in resp["error"]
        # non-object request
        resp = _raw_roundtrip(f, b"42\n")
        assert "malformed" in resp["error"]
        # params of the wrong shape
        resp = _raw_roundtrip(
            f, b'{"id": 2, "method": "bump", "params": [1, 2]}\n')
        assert "params" in resp["error"]
        # the SAME connection still serves valid calls (resynced)
        resp = _raw_roundtrip(
            f, b'{"id": 3, "method": "bump", "params": {}}\n')
        assert resp["result"] == {"calls": 1}
        f.close()
        s.close()

        # a fresh RpcClient sees the handler errors as RpcError
        c = RpcClient("127.0.0.1", server.port, timeout=5)
        with pytest.raises(RpcError, match="unknown method"):
            c.call("nope")
        assert c.call("bump")["calls"] == 2
        c.close()
    finally:
        server.stop()


def test_oversized_frame_is_rejected_and_connection_survives():
    import risingwave_tpu.cluster.rpc as rpc_mod

    target = _Counter()
    server = RpcServer(target).start()
    old = rpc_mod.MAX_FRAME_BYTES
    rpc_mod.MAX_FRAME_BYTES = 4096  # keep the test cheap
    try:
        s = socket.create_connection(("127.0.0.1", server.port),
                                     timeout=5)
        f = s.makefile("rwb")
        resp = _raw_roundtrip(f, b"x" * 20000 + b"\n")
        assert "oversized" in resp["error"]
        # resynced: the next valid frame answers
        resp = _raw_roundtrip(
            f, b'{"id": 1, "method": "bump", "params": {}}\n')
        assert resp["result"] == {"calls": 1}
        f.close()
        s.close()
    finally:
        rpc_mod.MAX_FRAME_BYTES = old
        server.stop()


def test_torn_frame_client_death_leaves_server_serving():
    """A client dying mid-frame (no newline ever arrives) must not
    wedge the accept loop for other clients."""
    target = _Counter()
    server = RpcServer(target).start()
    try:
        s = socket.create_connection(("127.0.0.1", server.port),
                                     timeout=5)
        s.sendall(b'{"id": 1, "method": "bu')  # torn: no newline
        s.close()  # peer dies mid-frame
        c = RpcClient("127.0.0.1", server.port, timeout=5)
        assert c.call("bump")["calls"] == 1
        c.close()
    finally:
        server.stop()


# -- store fabric hook ---------------------------------------------------
def test_global_fabric_injects_into_object_store():
    from risingwave_tpu.storage.hummock.object_store import (
        InMemObjectStore,
        ObjectError,
    )

    fab = faults_mod.install(FaultFabric())
    fab.fail_store("put", substr="epoch_", mode="before")
    fab.fail_store("put", substr="epoch_", mode="after")
    store = InMemObjectStore()
    with pytest.raises(ObjectError, match="lost"):
        store.put("job/epoch_3.npz", b"x")
    assert not store.exists("job/epoch_3.npz")  # lost BEFORE landing
    with pytest.raises(ObjectError, match="durable"):
        store.put("job/epoch_4.npz", b"y")
    assert store.exists("job/epoch_4.npz")  # landed, caller died
    store.put("job/epoch_5.npz", b"z")  # rules retired
    assert fab.injected_total() == 2
