"""Retractable MIN/MAX via materialized-input state (ref minput.rs).

Ground truth: python multisets replayed alongside the executor — every
flush's folded changelog must equal the brute-force min/max per group.
"""

from collections import Counter, defaultdict

import jax.numpy as jnp
import numpy as np
import pytest

import risingwave_tpu  # noqa: F401
from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.expr.agg import AggCall
from risingwave_tpu.expr.node import InputRef
from risingwave_tpu.stream.hash_agg import HashAggExecutor

SCHEMA = Schema((Field("g", DataType.INT64), Field("v", DataType.INT64)))


def make_chunk(rows, ops):
    cols = tuple(
        jnp.asarray([r[i] for r in rows] or [0], jnp.int64)
        for i in range(2)
    )
    return Chunk(
        cols,
        jnp.asarray(ops or [0], jnp.int8),
        jnp.asarray([True] * len(rows) or [False], jnp.bool_),
        SCHEMA,
    )


def fold(acc: dict, out: Chunk):
    """Fold a (g, min, max) changelog into {g: (min, max)}."""
    vis = np.asarray(out.valid)
    ops = np.asarray(out.ops)[vis]
    cols = [np.asarray(c)[vis] for c in out.columns]
    for i in range(len(ops)):
        g = int(cols[0][i])
        row = (int(cols[1][i]), int(cols[2][i]))
        if ops[i] in (0, 3):
            acc[g] = row
        else:
            if acc.get(g) == row:
                del acc[g]
    return acc


SCRIPT = [
    ([(1, 5), (1, 9), (2, 7)], [0, 0, 0]),
    ([(1, 3)], [0]),               # new min
    ([(1, 3)], [1]),               # delete the min -> recompute to 5
    ([(1, 9), (1, 5)], [1, 1]),    # group 1 empties
    ([(2, 7), (2, 7)], [0, 1]),    # in-chunk annihilation (dup value)
    ([(3, 4), (3, 4), (3, 6)], [0, 0, 0]),  # duplicate values
    ([(3, 4)], [1]),               # one duplicate leaves; min stays 4
    ([(3, 4)], [1]),               # the other leaves; min becomes 6
]


def test_retractable_minmax_ground_truth():
    agg = HashAggExecutor(
        SCHEMA,
        [("g", InputRef(0))],
        [AggCall("min", InputRef(1), "mn"), AggCall("max", InputRef(1), "mx")],
        table_size=64, emit_capacity=64,
        retractable_input=True, minput_bucket_cap=8,
    )
    st = agg.init_state()
    acc: dict = {}
    live = defaultdict(Counter)
    epoch = 0
    for rows, ops in SCRIPT:
        for (g, v), o in zip(rows, ops):
            if o == 0:
                live[g][v] += 1
            else:
                live[g][v] -= 1
        st, _ = agg.apply(st, make_chunk(rows, ops))
        epoch += 1
        st, out = agg.flush(st, epoch)
        fold(acc, out)
        want = {}
        for g, c in live.items():
            vals = list(c.elements())
            if vals:
                want[g] = (min(vals), max(vals))
        assert acc == want, f"after {rows} {ops}: {acc} != {want}"
    assert int(st.inconsistency) == 0
    assert int(st.overflow) == 0


def test_minput_bucket_overflow_is_loud():
    agg = HashAggExecutor(
        SCHEMA, [("g", InputRef(0))],
        [AggCall("min", InputRef(1), "mn")],
        table_size=64, emit_capacity=64,
        retractable_input=True, minput_bucket_cap=2,
    )
    st = agg.init_state()
    st, _ = agg.apply(st, make_chunk([(1, 1), (1, 2), (1, 3)], [0, 0, 0]))
    assert int(st.overflow) == 1  # third value found no bucket space


def test_sql_min_over_retractable_cascade():
    """MIN over an agg MV's changelog (a retractable stream): deletes
    recompute exactly instead of crashing the job."""
    from tests.test_dag import small_engine

    eng = small_engine()
    eng.execute("CREATE TABLE t (k BIGINT, v BIGINT);")
    eng.execute("""
        CREATE MATERIALIZED VIEW counts AS
        SELECT k, count(*) AS n FROM t GROUP BY k;
    """)
    eng.execute("""
        CREATE MATERIALIZED VIEW extremes AS
        SELECT min(n) AS mn, max(n) AS mx FROM counts;
    """)
    eng.execute("INSERT INTO t VALUES (1, 0), (1, 0), (2, 0)")
    eng.tick(barriers=2, chunks_per_barrier=1)
    # counts: {1: 2, 2: 1}
    assert eng.execute("SELECT * FROM extremes") == [(1, 2)]
    eng.execute("INSERT INTO t VALUES (2, 0), (2, 0)")
    eng.tick(barriers=2, chunks_per_barrier=1)
    # counts: {1: 2, 2: 3} — the old max row (2,1) was RETRACTED
    assert eng.execute("SELECT * FROM extremes") == [(2, 3)]
    eng.execute("INSERT INTO t VALUES (3, 0)")
    eng.tick(barriers=2, chunks_per_barrier=1)
    # counts: {1: 2, 2: 3, 3: 1} — min drops back to 1
    assert eng.execute("SELECT * FROM extremes") == [(1, 3)]
