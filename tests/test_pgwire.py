"""pgwire protocol tests with a raw-socket minimal client
(no postgres driver in the image; the client speaks protocol 3.0
simple-query flow exactly as psql would)."""

import socket
import struct

import pytest

from risingwave_tpu.server import SingleNode
from risingwave_tpu.sql.planner import PlannerConfig


class MiniPgClient:
    def __init__(self, host, port):
        self.sock = socket.create_connection((host, port), timeout=10)
        self.f = self.sock.makefile("rwb")
        self._startup()

    def _startup(self):
        params = b"user\x00tpu\x00database\x00dev\x00\x00"
        body = struct.pack("!I", 196608) + params
        self.f.write(struct.pack("!I", len(body) + 4) + body)
        self.f.flush()
        # read until ReadyForQuery
        while True:
            tag, payload = self._read_msg()
            if tag == b"Z":
                return

    def _read_msg(self):
        header = self.f.read(5)
        assert len(header) == 5, "connection closed"
        tag = header[:1]
        length = struct.unpack("!I", header[1:])[0]
        return tag, self.f.read(length - 4)

    def query(self, sql):
        body = sql.encode() + b"\x00"
        self.f.write(b"Q" + struct.pack("!I", len(body) + 4) + body)
        self.f.flush()
        cols, rows, error = [], [], None
        while True:
            tag, payload = self._read_msg()
            if tag == b"T":
                n = struct.unpack("!H", payload[:2])[0]
                off = 2
                for _ in range(n):
                    end = payload.index(b"\x00", off)
                    cols.append(payload[off:end].decode())
                    off = end + 1 + 18
            elif tag == b"D":
                n = struct.unpack("!H", payload[:2])[0]
                off = 2
                row = []
                for _ in range(n):
                    ln = struct.unpack("!i", payload[off:off + 4])[0]
                    off += 4
                    if ln < 0:
                        row.append(None)
                    else:
                        row.append(payload[off:off + ln].decode())
                        off += ln
                rows.append(tuple(row))
            elif tag == b"E":
                error = payload.decode(errors="replace")
            elif tag == b"Z":
                if error:
                    raise RuntimeError(error)
                return cols, rows

    def close(self):
        self.f.write(b"X" + struct.pack("!I", 4))
        self.f.flush()
        self.sock.close()


@pytest.fixture()
def node():
    n = SingleNode(PlannerConfig(
        chunk_capacity=128, agg_table_size=256, agg_emit_capacity=64,
        mv_table_size=256, mv_ring_size=1024,
    ))
    # port 0 = ephemeral
    server = n.start(port=0, ticker=False)  # deterministic ticks
    host, port = server.server_address
    yield n, host, port
    n.stop()
    server.shutdown()


def test_pgwire_end_to_end(node):
    n, host, port = node
    c = MiniPgClient(host, port)
    try:
        c.query("""
            CREATE SOURCE t (k BIGINT, v BIGINT)
            WITH (connector = 'datagen')
        """)
        c.query("""
            CREATE MATERIALIZED VIEW m AS
            SELECT k % 2 AS b, count(*) AS n FROM t GROUP BY k % 2
        """)
        # drive the dataflow deterministically (the background ticker
        # paces at barrier_interval_ms; FLUSH-style direct ticks are
        # exact for the assertion)
        n.tick(barriers=2, chunks_per_barrier=1)
        cols, rows = c.query("SELECT b, n FROM m ORDER BY b")
        assert cols == ["b", "n"]
        assert [(r[0], r[1]) for r in rows] == [("0", "128"), ("1", "128")]

        cols, rows = c.query("SHOW MATERIALIZED VIEWS")
        assert rows == [("m",)]
    finally:
        c.close()


def test_pgwire_error_keeps_session(node):
    n, host, port = node
    c = MiniPgClient(host, port)
    try:
        with pytest.raises(RuntimeError):
            c.query("SELECT broken FROM nowhere")
        # session still usable after an error
        cols, rows = c.query("SHOW SOURCES")
        assert rows == []
    finally:
        c.close()


def test_pgwire_concurrent_sessions(node):
    n, host, port = node
    a = MiniPgClient(host, port)
    b = MiniPgClient(host, port)
    try:
        a.query("CREATE SOURCE s1 (k BIGINT) WITH (connector='datagen')")
        b.query("CREATE SOURCE s2 (k BIGINT) WITH (connector='datagen')")
        _, rows = a.query("SHOW SOURCES")
        assert sorted(rows) == [("s1",), ("s2",)]
    finally:
        a.close()
        b.close()


def test_background_ticker_advances_jobs():
    """The barrier ticker (barrier_interval_ms) drives jobs on its own."""
    import time

    n = SingleNode(PlannerConfig(
        chunk_capacity=64, agg_table_size=256, agg_emit_capacity=64,
        mv_table_size=256, mv_ring_size=1024,
    ))
    n.engine.system_params.set("barrier_interval_ms", 50)
    server = n.start(port=0)
    try:
        host, port = server.server_address
        c = MiniPgClient(host, port)
        c.query("CREATE SOURCE t (k BIGINT) WITH (connector='datagen')")
        c.query("CREATE MATERIALIZED VIEW m AS SELECT count(*) AS n FROM t")
        deadline = time.time() + 15
        total = 0
        while time.time() < deadline:
            _, rows = c.query("SELECT n FROM m")
            if rows and int(rows[0][0]) > 0:
                total = int(rows[0][0])
                break
            time.sleep(0.1)
        assert total > 0  # the ticker advanced the dataflow by itself
        c.close()
    finally:
        n.stop()
        server.shutdown()
