"""pgwire protocol tests with a raw-socket minimal client
(no postgres driver in the image; the client speaks protocol 3.0
simple-query flow exactly as psql would)."""

import socket
import struct

import pytest

from risingwave_tpu.server import SingleNode
from risingwave_tpu.sql.planner import PlannerConfig


from risingwave_tpu.pgwire import SimpleClient as MiniPgClient  # noqa: E402


@pytest.fixture()
def node():
    n = SingleNode(PlannerConfig(
        chunk_capacity=128, agg_table_size=256, agg_emit_capacity=64,
        mv_table_size=256, mv_ring_size=1024,
    ))
    # port 0 = ephemeral
    server = n.start(port=0, ticker=False)  # deterministic ticks
    host, port = server.server_address
    yield n, host, port
    n.stop()
    server.shutdown()


def test_pgwire_end_to_end(node):
    n, host, port = node
    c = MiniPgClient(host, port)
    try:
        c.query("""
            CREATE SOURCE t (k BIGINT, v BIGINT)
            WITH (connector = 'datagen')
        """)
        c.query("""
            CREATE MATERIALIZED VIEW m AS
            SELECT k % 2 AS b, count(*) AS n FROM t GROUP BY k % 2
        """)
        # drive the dataflow deterministically (the background ticker
        # paces at barrier_interval_ms; FLUSH-style direct ticks are
        # exact for the assertion)
        n.tick(barriers=2, chunks_per_barrier=1)
        cols, rows = c.query("SELECT b, n FROM m ORDER BY b")
        assert cols == ["b", "n"]
        assert [(r[0], r[1]) for r in rows] == [("0", "128"), ("1", "128")]

        cols, rows = c.query("SHOW MATERIALIZED VIEWS")
        assert rows == [("m",)]
    finally:
        c.close()


def test_pgwire_error_keeps_session(node):
    n, host, port = node
    c = MiniPgClient(host, port)
    try:
        with pytest.raises(RuntimeError):
            c.query("SELECT broken FROM nowhere")
        # session still usable after an error
        cols, rows = c.query("SHOW SOURCES")
        assert rows == []
    finally:
        c.close()


def test_pgwire_concurrent_sessions(node):
    n, host, port = node
    a = MiniPgClient(host, port)
    b = MiniPgClient(host, port)
    try:
        a.query("CREATE SOURCE s1 (k BIGINT) WITH (connector='datagen')")
        b.query("CREATE SOURCE s2 (k BIGINT) WITH (connector='datagen')")
        _, rows = a.query("SHOW SOURCES")
        assert sorted(rows) == [("s1",), ("s2",)]
    finally:
        a.close()
        b.close()


def test_pgwire_extended_protocol(node):
    """Parse/Bind/Describe/Execute/Sync with a parameter — the message
    flow psycopg/JDBC default to (ref pg_protocol.rs:340,
    e2e_extended_mode)."""
    n, host, port = node
    c = MiniPgClient(host, port)
    try:
        c.query("CREATE TABLE t (k BIGINT, v BIGINT)")
        c.query("INSERT INTO t VALUES (1,10),(2,20),(1,30),(3,7)")
        c.query("""
            CREATE MATERIALIZED VIEW m AS
            SELECT k, count(*) AS n, sum(v) AS s FROM t GROUP BY k
        """)
        c.query("FLUSH")
        cols, rows = c.execute_prepared(
            "SELECT n, s FROM m WHERE k = $1", params=(1,)
        )
        assert cols == ["n", "s"]
        assert rows == [("2", "40")]
        # string parameter quoting round-trips
        cols, rows = c.execute_prepared(
            "SELECT count(*) AS c FROM m WHERE k = $1 OR k = $2",
            params=(2, 3),
        )
        assert rows == [("2",)]
        # error inside a batch discards until Sync; session survives
        with pytest.raises(RuntimeError):
            c.execute_prepared("SELECT nope FROM nowhere")
        _, rows = c.execute_prepared("SELECT k FROM m WHERE k = $1",
                                     params=(3,))
        assert rows == [("3",)]
    finally:
        c.close()


def test_pgwire_cleartext_auth():
    """Password-gated startup (AuthenticationCleartextPassword)."""
    from risingwave_tpu.sql import Engine

    from risingwave_tpu.pgwire import pg_serve

    eng = Engine(PlannerConfig(
        chunk_capacity=64, agg_table_size=256, agg_emit_capacity=64,
        mv_table_size=256, mv_ring_size=1024,
    ))
    server = pg_serve(eng, port=0, password="sekret")
    try:
        host, port = server.server_address
        c = MiniPgClient(host, port, password="sekret")
        _, rows = c.query("SHOW SOURCES")
        assert rows == []
        c.close()
        with pytest.raises((RuntimeError, ConnectionError)):
            MiniPgClient(host, port, password="wrong")
    finally:
        server.shutdown()


def test_background_ticker_advances_jobs():
    """The barrier ticker (barrier_interval_ms) drives jobs on its own."""
    import time

    n = SingleNode(PlannerConfig(
        chunk_capacity=64, agg_table_size=256, agg_emit_capacity=64,
        mv_table_size=256, mv_ring_size=1024,
    ))
    n.engine.system_params.set("barrier_interval_ms", 50)
    server = n.start(port=0)
    try:
        host, port = server.server_address
        c = MiniPgClient(host, port)
        c.query("CREATE SOURCE t (k BIGINT) WITH (connector='datagen')")
        c.query("CREATE MATERIALIZED VIEW m AS SELECT count(*) AS n FROM t")
        deadline = time.time() + 15
        total = 0
        while time.time() < deadline:
            _, rows = c.query("SELECT n FROM m")
            if rows and int(rows[0][0]) > 0:
                total = int(rows[0][0])
                break
            time.sleep(0.1)
        assert total > 0  # the ticker advanced the dataflow by itself
        c.close()
    finally:
        n.stop()
        server.shutdown()
