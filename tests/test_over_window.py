"""OverWindow executor tests vs numpy/pandas-style ground truth."""

from collections import Counter

import numpy as np

from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.expr.node import col
from risingwave_tpu.stream.fragment import Fragment
from risingwave_tpu.stream.over_window import (
    OverWindowExecutor,
    WindowFuncCall,
)

S = Schema.of(("p", DataType.INT64), ("v", DataType.INT64))


def _chunk(text):
    return Chunk.from_pretty(text, names=["p", "v"])


def _mv(counter, out):
    for op, *vals in out.to_rows():
        if op in (0, 3):
            counter[tuple(vals)] += 1
        else:
            counter[tuple(vals)] -= 1
    return +counter


def _exec(calls, **kw):
    ow = OverWindowExecutor(
        S, partition_by=[col("p")], order_by=[(col("v"), False)],
        calls=calls, pool_size=64, emit_capacity=32, **kw,
    )
    return Fragment([ow])


def test_row_number_and_running_sum():
    frag = _exec([
        WindowFuncCall("row_number", alias="rn"),
        WindowFuncCall("sum", col("v"), alias="s"),
        WindowFuncCall("count", alias="c"),
    ])
    st = frag.init_states()
    st, _ = frag.step(st, _chunk("""
        I I
        + 1 30
        + 1 10
        + 2 5
        + 1 20
    """))
    st, outs = frag.flush(st, 1)
    mv = _mv(Counter(), outs[0])
    assert mv == Counter({
        (1, 10, 1, 10, 1): 1,
        (1, 20, 2, 30, 2): 1,
        (1, 30, 3, 60, 3): 1,
        (2, 5, 1, 5, 1): 1,
    })

    # a new row re-ranks its partition; changelog updates only partition 1
    st, _ = frag.step(st, _chunk("""
        I I
        + 1 15
    """))
    st, outs = frag.flush(st, 2)
    mv = _mv(mv, outs[0])
    assert mv == Counter({
        (1, 10, 1, 10, 1): 1,
        (1, 15, 2, 25, 2): 1,
        (1, 20, 3, 45, 3): 1,
        (1, 30, 4, 75, 4): 1,
        (2, 5, 1, 5, 1): 1,
    })


def test_rank_dense_rank_with_ties():
    frag = _exec([
        WindowFuncCall("rank", alias="r"),
        WindowFuncCall("dense_rank", alias="d"),
    ])
    st = frag.init_states()
    st, _ = frag.step(st, _chunk("""
        I I
        + 1 10
        + 1 10
        + 1 20
        + 1 30
    """))
    st, outs = frag.flush(st, 1)
    mv = _mv(Counter(), outs[0])
    assert mv == Counter({
        (1, 10, 1, 1): 2,   # tie: both rank 1, dense 1
        (1, 20, 3, 2): 1,   # rank skips, dense doesn't
        (1, 30, 4, 3): 1,
    })


def test_lag_lead_partition_boundaries():
    frag = _exec([
        WindowFuncCall("lag", col("v"), alias="lg"),
        WindowFuncCall("lead", col("v"), alias="ld"),
    ])
    st = frag.init_states()
    st, _ = frag.step(st, _chunk("""
        I I
        + 1 10
        + 1 20
        + 2 7
    """))
    st, outs = frag.flush(st, 1)
    mv = _mv(Counter(), outs[0])
    # lag/lead are 0 (NULL placeholder) outside the partition
    assert mv == Counter({
        (1, 10, 0, 20): 1,
        (1, 20, 10, 0): 1,
        (2, 7, 0, 0): 1,
    })


def test_running_min_max():
    frag = _exec([
        WindowFuncCall("min", col("v"), alias="lo"),
        WindowFuncCall("max", col("v"), alias="hi"),
    ])
    st = frag.init_states()
    st, _ = frag.step(st, _chunk("""
        I I
        + 1 20
        + 1 10
        + 1 30
    """))
    st, outs = frag.flush(st, 1)
    mv = _mv(Counter(), outs[0])
    # ordered asc by v: running min stays 10..., max grows
    assert mv == Counter({
        (1, 10, 10, 10): 1,
        (1, 20, 10, 20): 1,
        (1, 30, 10, 30): 1,
    })


def test_retraction_rerank():
    frag = _exec([WindowFuncCall("row_number", alias="rn")])
    st = frag.init_states()
    st, _ = frag.step(st, _chunk("""
        I I
        + 1 10
        + 1 20
        + 1 30
    """))
    st, outs = frag.flush(st, 1)
    mv = _mv(Counter(), outs[0])
    st, _ = frag.step(st, _chunk("""
        I I
        - 1 10
    """))
    st, outs = frag.flush(st, 2)
    mv = _mv(mv, outs[0])
    assert mv == Counter({(1, 20, 1): 1, (1, 30, 2): 1})
