"""Test harness configuration.

Multi-chip behaviour is tested on a virtual 8-device CPU mesh (the
driver's dryrun does the same), mirroring how the reference tests
multi-node behaviour in a single process with madsim (SURVEY.md §4.4).
Must run before jax initializes.
"""

import os

# force CPU: the ambient environment pins JAX_PLATFORMS=axon (remote TPU
# tunnel), which would send every test compile over the wire
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# the ambient TPU-tunnel plugin overrides jax_platforms to "axon,cpu" at
# interpreter start; force pure-CPU here so tests never touch the tunnel
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# NOTE: do NOT enable jax's persistent compilation cache
# (jax_compilation_cache_dir) for this suite: on the baked-in jax
# 0.4.37 CPU build, cache-served executables return corrupted outputs
# for the donated streaming-state programs (observed: garbage overflow
# counters in test_cold_start/test_chaos on the second run), turning
# correct code into red tests.


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running stress tests excluded from the tier-1 run",
    )
