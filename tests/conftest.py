"""Test harness configuration.

Multi-chip behaviour is tested on a virtual 8-device CPU mesh (the
driver's dryrun does the same), mirroring how the reference tests
multi-node behaviour in a single process with madsim (SURVEY.md §4.4).
Must run before jax initializes.
"""

import os

# force CPU: the ambient environment pins JAX_PLATFORMS=axon (remote TPU
# tunnel), which would send every test compile over the wire
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# the ambient TPU-tunnel plugin overrides jax_platforms to "axon,cpu" at
# interpreter start; force pure-CPU here so tests never touch the tunnel
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# NOTE: do NOT enable jax's persistent compilation cache
# (jax_compilation_cache_dir) for this suite: on the baked-in jax
# 0.4.37 CPU build, cache-served executables return corrupted outputs
# for the donated streaming-state programs (observed: garbage overflow
# counters in test_cold_start/test_chaos on the second run), turning
# correct code into red tests.  Enforced below — a configured cache
# fails the session at start instead of producing flaky green/red runs.


def _assert_no_persistent_compilation_cache():
    import pytest

    cache_dir = (
        os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or getattr(jax.config, "jax_compilation_cache_dir", None)
    )
    if cache_dir:
        pytest.exit(
            "jax persistent compilation cache is enabled "
            f"(jax_compilation_cache_dir={cache_dir!r}), but on this "
            "jax 0.4.37 CPU build cache-served executables corrupt "
            "donated streaming-state program outputs (garbage "
            "overflow counters — see CHANGES.md PR 2).  Unset "
            "JAX_COMPILATION_CACHE_DIR to run the suite.",
            returncode=3,
        )


def pytest_configure(config):
    _assert_no_persistent_compilation_cache()
    config.addinivalue_line(
        "markers",
        "slow: long-running stress tests excluded from the tier-1 run",
    )
