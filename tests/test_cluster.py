"""Cluster-lite control plane: RPC, placement, global epoch commit,
heartbeat failover (in-process workers; process-level SIGKILL chaos
lives in test_chaos.py)."""

import time

import pytest

from risingwave_tpu.cluster import (
    ComputeWorker,
    MetaService,
    RpcClient,
    RpcError,
    RpcServer,
)
from risingwave_tpu.common.config import RwConfig


def _cfg():
    return RwConfig.from_dict({
        "streaming": {"chunk_size": 128},
        "state": {"agg_table_size": 512, "agg_emit_capacity": 128,
                  "mv_table_size": 512, "mv_ring_size": 1024},
        "storage": {"checkpoint_keep_epochs": 4},
    })


def _rows(served):
    return sorted(tuple(r) for r in served[1])


def _single_rows(eng, sql):
    return sorted(tuple(int(v) for v in r) for r in eng.execute(sql))


# -- transport -----------------------------------------------------------
class _EchoTarget:
    def rpc_echo(self, x):
        return {"x": x}

    def rpc_boom(self):
        raise ValueError("no")


def test_rpc_roundtrip_and_errors():
    server = RpcServer(_EchoTarget()).start()
    try:
        c = RpcClient("127.0.0.1", server.port, timeout=5)
        assert c.call("echo", x=[1, "a", None]) == {"x": [1, "a", None]}
        with pytest.raises(RpcError, match="no"):
            c.call("boom")
        with pytest.raises(RpcError, match="unknown method"):
            c.call("nope")
        # the connection survives remote errors
        assert c.call("echo", x=2) == {"x": 2}
        c.close()
    finally:
        server.stop()


# -- the full control-plane loop -----------------------------------------
def test_cluster_commit_failover_convergence(tmp_path):
    """1 meta + 2 in-process workers, 2 MVs: global rounds commit ONE
    cluster epoch; a silently-dying worker is expired by heartbeat
    timeout, its job reassigned and replayed from the last committed
    epoch; final MV contents match an undisturbed single-node run."""
    from risingwave_tpu.sql.engine import Engine

    ddl = [
        "CREATE SOURCE t (k BIGINT, v BIGINT) "
        "WITH (connector='datagen')",
        "CREATE MATERIALIZED VIEW m1 AS "
        "SELECT k % 8 AS g, count(*) AS n FROM t GROUP BY k % 8",
        "CREATE MATERIALIZED VIEW m2 AS "
        "SELECT k % 4 AS g, sum(v) AS s FROM t GROUP BY k % 4",
    ]
    meta = MetaService(str(tmp_path), heartbeat_timeout_s=1.0)
    meta.start(port=0)
    addr = f"127.0.0.1:{meta.rpc_port}"
    w1 = ComputeWorker(addr, str(tmp_path), config=_cfg(),
                       heartbeat_interval_s=0.2).start()
    w2 = ComputeWorker(addr, str(tmp_path), config=_cfg(),
                       heartbeat_interval_s=0.2).start()
    try:
        for sql in ddl:
            meta.execute_ddl(sql)
        jobs = {j["name"]: j for j in meta.state()["jobs"]}
        # job-level placement spreads jobs across both workers
        assert jobs["m1"]["worker"] != jobs["m2"]["worker"]

        for _ in range(3):
            res = meta.tick(1)
            assert res["committed"], res
        assert meta.cluster_epoch == 3
        # the cluster epoch is durable in the shared version manifest
        assert meta.versions.max_committed_epoch > 0

        # reads route through the pinned epoch (committed state only)
        assert _rows(meta.serve("SELECT g, n FROM m1")) == [
            (g, 48) for g in range(8)
        ]

        # kill the worker owning m2 WITHOUT stopping heartbeats cleanly
        victim, survivor = (w1, w2) \
            if jobs["m2"]["worker"] == w1.worker_id else (w2, w1)
        victim.stop()
        deadline = time.monotonic() + 10
        while meta.failovers == 0:
            meta.check_heartbeats()
            assert time.monotonic() < deadline, "failover never fired"
            time.sleep(0.1)

        # incomplete rounds must not advance the cluster epoch
        for _ in range(3):
            deadline = time.monotonic() + 30
            while True:
                res = meta.tick(1)
                if res["committed"]:
                    break
                meta.check_heartbeats()
                assert time.monotonic() < deadline
                time.sleep(0.1)
        assert meta.cluster_epoch == 6
        st = {j["name"]: j for j in meta.state()["jobs"]}
        assert st["m2"]["worker"] == survivor.worker_id

        got1 = _rows(meta.serve("SELECT g, n FROM m1"))
        got2 = _rows(meta.serve("SELECT g, s FROM m2"))

        # undisturbed single-node reference: same config, same rounds
        eng = Engine(_cfg())
        for sql in ddl:
            eng.execute(sql)
        eng.tick(barriers=6, chunks_per_barrier=1)
        assert got1 == _single_rows(eng, "SELECT g, n FROM m1")
        assert got2 == _single_rows(eng, "SELECT g, s FROM m2")
        assert meta.failovers == 1
    finally:
        w1.stop()
        w2.stop()
        meta.stop()


def test_mv_on_mv_colocates_and_serves(tmp_path):
    """An MV over another MV lands on the upstream's job/worker (the
    engine attaches it to the same DagJob there); both serve."""
    meta = MetaService(str(tmp_path), heartbeat_timeout_s=5.0)
    meta.start(port=0, monitor=False)
    addr = f"127.0.0.1:{meta.rpc_port}"
    w1 = ComputeWorker(addr, str(tmp_path), config=_cfg(),
                       heartbeat_interval_s=0.5).start()
    w2 = ComputeWorker(addr, str(tmp_path), config=_cfg(),
                       heartbeat_interval_s=0.5).start()
    try:
        meta.execute_ddl(
            "CREATE SOURCE t (k BIGINT, v BIGINT) "
            "WITH (connector='datagen')"
        )
        meta.execute_ddl(
            "CREATE MATERIALIZED VIEW base AS "
            "SELECT k % 4 AS g, count(*) AS n FROM t GROUP BY k % 4"
        )
        meta.execute_ddl(
            "CREATE MATERIALIZED VIEW top1 AS "
            "SELECT g, n FROM base WHERE g < 2"
        )
        st = meta.state()
        jobs = {j["name"]: j for j in st["jobs"]}
        assert "top1" not in jobs  # rides the upstream job
        assert jobs["base"]["mvs"] == ["base", "top1"]
        for _ in range(2):
            assert meta.tick(1)["committed"]
        assert _rows(meta.serve("SELECT g, n FROM base")) == [
            (g, 64) for g in range(4)
        ]
        assert _rows(meta.serve("SELECT g, n FROM top1")) == [
            (0, 64), (1, 64)
        ]
    finally:
        w1.stop()
        w2.stop()
        meta.stop()


def test_insert_forwarding_reaches_table_hosts(tmp_path):
    """INSERTs fan out to the workers whose catalogs hold the table;
    the owning job materializes them on the next global round."""
    meta = MetaService(str(tmp_path), heartbeat_timeout_s=5.0)
    meta.start(port=0, monitor=False)
    addr = f"127.0.0.1:{meta.rpc_port}"
    w1 = ComputeWorker(addr, str(tmp_path), config=_cfg(),
                       heartbeat_interval_s=0.5).start()
    try:
        meta.execute_ddl("CREATE TABLE dt (k BIGINT, v BIGINT)")
        with pytest.raises(ValueError, match="no live worker"):
            meta.execute_ddl("INSERT INTO dt VALUES (0, 0)")
        meta.execute_ddl(
            "CREATE MATERIALIZED VIEW dv AS "
            "SELECT k, sum(v) AS s FROM dt GROUP BY k"
        )
        meta.execute_ddl(
            "INSERT INTO dt VALUES (1, 10), (1, 5), (2, 7)"
        )
        for _ in range(2):
            assert meta.tick(1)["committed"]
        assert _rows(meta.serve("SELECT k, s FROM dv")) == [
            (1, 15), (2, 7)
        ]
        # the statement is durable in the meta's DML log
        assert meta.store.dml_sql_log() == [
            "INSERT INTO dt VALUES (1, 10), (1, 5), (2, 7)"
        ]
    finally:
        w1.stop()
        meta.stop()


def test_engine_export_adopt_roundtrip(tmp_path):
    """Engine-level reassignment primitive: export a job's DDL from
    one engine, adopt it on a fresh compute-role engine over the same
    data_dir — state and source cursor resume at the exported
    engine's last committed epoch."""
    from risingwave_tpu.sql.engine import Engine

    eng = Engine(_cfg(), data_dir=str(tmp_path))
    eng.execute(
        "CREATE SOURCE t (k BIGINT, v BIGINT) "
        "WITH (connector='datagen');"
        "CREATE MATERIALIZED VIEW em AS "
        "SELECT k % 4 AS g, count(*) AS n FROM t GROUP BY k % 4"
    )
    eng.tick(barriers=2, chunks_per_barrier=1)
    ddl = eng.export_job_ddl("em")
    assert len(ddl) == 2 and "CREATE MATERIALIZED VIEW" in ddl[1]

    adoptee = Engine(_cfg(), data_dir=str(tmp_path), role="compute")
    # compute role: no meta store / no hummock manifest of its own
    assert adoptee.meta_store is None and adoptee.hummock is None
    epoch = adoptee.adopt_job(ddl, "em")
    assert epoch == eng.jobs[0].committed_epoch > 0
    assert _single_rows(adoptee, "SELECT g, n FROM em") \
        == _single_rows(eng, "SELECT g, n FROM em")
    # adoption is idempotent for already-present DDL
    assert adoptee.adopt_job(ddl, "em") == epoch


def test_serve_unknown_mv_is_final_error(tmp_path):
    meta = MetaService(str(tmp_path), serve_retry_timeout_s=0.5)
    meta.start(port=0, monitor=False)
    try:
        with pytest.raises(ValueError, match="not a placed MV"):
            meta.serve("SELECT * FROM nope")
    finally:
        meta.stop()


# -- chaos-lite robustness (ISSUE 6) -------------------------------------
def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_worker_heartbeat_survives_meta_socket_pause(tmp_path):
    """ISSUE 6 satellite: pause the meta's RPC socket mid-flight — the
    worker's heartbeat thread must SURVIVE the unreachable window (no
    silent death) and resume beating once the socket returns, with
    the original registration intact."""
    from risingwave_tpu.cluster.rpc import RpcServer

    port = _free_port()
    meta = MetaService(str(tmp_path), heartbeat_timeout_s=60.0)
    meta.start(port=port, monitor=False, compactor=False)
    w = ComputeWorker(f"127.0.0.1:{port}", str(tmp_path),
                      config=_cfg(), heartbeat_interval_s=0.1).start()
    try:
        deadline = time.monotonic() + 10
        while w.heartbeats_sent == 0:
            assert time.monotonic() < deadline
            time.sleep(0.05)

        # pause: the meta socket goes away but the meta LIVES.  The
        # listener stops accepting AND the worker's established
        # connection is severed (stop() alone leaves per-connection
        # handler threads serving) — the full dropped-socket picture.
        meta._server.stop()
        meta._server = None
        w._meta_client.close()
        deadline = time.monotonic() + 10
        while w.heartbeat_failures < 2:
            assert time.monotonic() < deadline, \
                "heartbeat thread died instead of backing off"
            time.sleep(0.05)
        assert w._hb_thread.is_alive()

        # resume on the SAME port: beats flow again, same registration
        meta._server = RpcServer(meta, "127.0.0.1", port).start()
        sent = w.heartbeats_sent
        deadline = time.monotonic() + 10
        while w.heartbeats_sent <= sent:
            assert time.monotonic() < deadline, \
                "heartbeats never resumed after the pause"
            time.sleep(0.05)
        assert w.registrations == 1  # the meta never forgot us
        assert meta.workers[w.worker_id].alive
    finally:
        w.stop()
        meta.stop()


def test_barrier_retry_with_lost_response_is_idempotent(tmp_path):
    """Round-tagged barriers: a barrier whose RESPONSE is injected
    away is retried by the meta's RetryPolicy and answered from the
    worker's round cache — the chunks run exactly once, and the final
    MV matches the undisturbed single-node run."""
    from risingwave_tpu.common import faults as faults_mod
    from risingwave_tpu.common.faults import FaultFabric
    from risingwave_tpu.sql.engine import Engine

    meta = MetaService(str(tmp_path), heartbeat_timeout_s=60.0)
    meta.start(port=0, monitor=False, compactor=False)
    addr = f"127.0.0.1:{meta.rpc_port}"
    w = ComputeWorker(addr, str(tmp_path), config=_cfg(),
                      heartbeat_interval_s=5.0).start()
    try:
        meta.execute_ddl(
            "CREATE SOURCE t (k BIGINT, v BIGINT) "
            "WITH (connector='datagen')"
        )
        meta.execute_ddl(
            "CREATE MATERIALIZED VIEW rm AS "
            "SELECT k % 4 AS g, count(*) AS n FROM t GROUP BY k % 4"
        )
        assert meta.tick(1)["committed"]

        fab = faults_mod.install(FaultFabric())
        # the next TWO barrier responses are lost after execution
        fab.fail_rpc(substr=">worker1/barrier", mode="error_after_send",
                     times=2)
        try:
            for _ in range(2):
                res = meta.tick(1)
                assert res["committed"], res
        finally:
            faults_mod.install(None)
        assert fab.injected.get("rpc", 0) == 2
        assert meta.retry.retries >= 2
        assert meta.cluster_epoch == 3

        got = _rows(meta.serve("SELECT g, n FROM rm"))
        eng = Engine(_cfg())
        eng.execute(
            "CREATE SOURCE t (k BIGINT, v BIGINT) "
            "WITH (connector='datagen');"
            "CREATE MATERIALIZED VIEW rm AS "
            "SELECT k % 4 AS g, count(*) AS n FROM t GROUP BY k % 4"
        )
        eng.tick(barriers=3, chunks_per_barrier=1)
        assert got == _single_rows(eng, "SELECT g, n FROM rm")
    finally:
        faults_mod.install(None)
        w.stop()
        meta.stop()


def test_meta_restart_recovers_and_workers_reregister(tmp_path):
    """The ISSUE 6 tentpole, in-process: crash the meta after 3
    committed rounds, boot a FRESH MetaService over the same data_dir
    on the same port — it rebuilds jobs + round position from the
    durable MetaStore/manifest, the workers' heartbeat loops detect
    the unknown-worker answer and re-register with backoff, jobs are
    re-adopted from the durable checkpoint chain, and 3 more rounds
    commit with byte-identical convergence.  No operator action."""
    from risingwave_tpu.sql.engine import Engine

    ddl = [
        "CREATE SOURCE t (k BIGINT, v BIGINT) "
        "WITH (connector='datagen')",
        "CREATE MATERIALIZED VIEW m1 AS "
        "SELECT k % 8 AS g, count(*) AS n FROM t GROUP BY k % 8",
        "CREATE MATERIALIZED VIEW m2 AS "
        "SELECT k % 4 AS g, sum(v) AS s FROM t GROUP BY k % 4",
    ]
    port = _free_port()
    meta = MetaService(str(tmp_path), heartbeat_timeout_s=30.0)
    meta.start(port=port, monitor=False, compactor=False)
    addr = f"127.0.0.1:{port}"
    w1 = ComputeWorker(addr, str(tmp_path), config=_cfg(),
                       heartbeat_interval_s=0.1).start()
    w2 = ComputeWorker(addr, str(tmp_path), config=_cfg(),
                       heartbeat_interval_s=0.1).start()
    meta2 = None
    try:
        for sql in ddl:
            meta.execute_ddl(sql)
        for _ in range(3):
            assert meta.tick(1)["committed"]
        want_epoch = meta.cluster_epoch
        assert want_epoch == 3

        # "SIGKILL": every in-memory structure dies with the object
        # (all durable writes were fsync'd at append time).  Sever the
        # workers' established connections too — stop() leaves the old
        # per-connection handler threads serving, which a real process
        # death would not (the subprocess campaign covers true SIGKILL)
        meta.stop()
        w1._meta_client.close()
        w2._meta_client.close()

        meta2 = MetaService(str(tmp_path), heartbeat_timeout_s=30.0)
        assert meta2.recovered
        assert meta2.cluster_epoch == 3  # round position recovered
        assert set(meta2.jobs) == {"m1", "m2"}  # catalog recovered
        assert all(j.worker_id is None for j in meta2.jobs.values())
        meta2.start(port=port, monitor=False, compactor=False)

        # workers re-register through their heartbeat loops (the old
        # ids answer "unknown worker" → RpcError → re-register).
        # Generous deadline: on a loaded 1-core box the re-adoption
        # recovery loads can push past 30s
        deadline = time.monotonic() + 90
        while len(meta2.live_workers()) < 2 or any(
                j.worker_id is None for j in meta2.jobs.values()):
            meta2.check_heartbeats()  # drives _assign_pending
            assert time.monotonic() < deadline, \
                "workers never re-registered / jobs never re-adopted"
            time.sleep(0.1)
        assert w1.registrations == 2 and w2.registrations == 2

        # the interrupted stream RESUMES committing cluster epochs
        for _ in range(3):
            deadline = time.monotonic() + 60
            while True:
                if meta2.tick(1)["committed"]:
                    break
                assert time.monotonic() < deadline
                time.sleep(0.1)
        assert meta2.cluster_epoch == 6

        got1 = _rows(meta2.serve("SELECT g, n FROM m1"))
        got2 = _rows(meta2.serve("SELECT g, s FROM m2"))
        eng = Engine(_cfg())
        for sql in ddl:
            eng.execute(sql)
        eng.tick(barriers=6, chunks_per_barrier=1)
        assert got1 == _single_rows(eng, "SELECT g, n FROM m1")
        assert got2 == _single_rows(eng, "SELECT g, s FROM m2")
    finally:
        w1.stop()
        w2.stop()
        if meta2 is not None:
            meta2.stop()
