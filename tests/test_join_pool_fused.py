"""Fused (hash, rank) pool join: changelog equivalence vs the dense
bucket path, probe-count guarantees, bump allocation, and compaction.

The PR-2 tentpole rebuilt the append-only pool side around ONE fused
(key-hash, rank) table + a bump-allocated row pool (see
stream/hash_join.py PoolSideState).  The dense bucket path is the
unchanged reference implementation, so these tests pin the new design
to it: identical folded changelogs across the join matrix, including
burst drains (tiny emission windows) and outer-join retraction
cascades driven from a retractable dense side.
"""

from collections import Counter

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import risingwave_tpu  # noqa: F401
from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.expr.node import col
from risingwave_tpu.stream.hash_join import HashJoinExecutor

from tests.test_join_matrix import fold

L = Schema.of(("k", DataType.INT64), ("a", DataType.INT64))
R = Schema.of(("k", DataType.INT64), ("b", DataType.INT64))


def _chunk(schema, rows, ops):
    names = [f.name for f in schema]
    txt = "I I\n" + "\n".join(
        f"{'+' if o == 0 else '-'} {r[0]} {r[1]}"
        for r, o in zip(rows, ops)
    )
    return Chunk.from_pretty(txt, names=names)


def _executor(storage, join_type, out_capacity):
    kw = dict(
        table_size=256, bucket_cap=64, out_capacity=out_capacity,
        join_type=join_type,
    )
    if storage == "pool":
        kw.update(
            left_storage="pool", right_storage="pool",
            left_pool_size=2048, right_pool_size=2048,
        )
    return HashJoinExecutor(L, R, [col("k")], [col("k")], **kw)


def _drain_all(j, st, chunk, side, acc):
    st, pending = j.apply_begin(st, chunk, side)
    build = j.build_rows_of(st, side)
    total = int(pending.total)
    w = 0
    while w == 0 or w * j.out_capacity < total:
        out, probe_bound = j.emit_window(build, pending, jnp.int32(w), side)
        assert int(probe_bound) == 0
        fold(acc, out)
        w += 1
    return st


def _append_script(seed, chunks=5, cap=16):
    """Skewed append-only scripts for both sides (one hot key)."""
    rng = np.random.default_rng(seed)
    script = []
    for i in range(chunks):
        side = "left" if i % 2 == 0 else "right"
        keys = np.where(
            rng.random(cap) < 0.5, 7, rng.integers(0, 6, cap)
        ).astype(np.int64)
        vals = rng.integers(0, 1000, cap).astype(np.int64)
        script.append((side, list(zip(keys.tolist(), vals.tolist())),
                       [0] * cap))
    return script


@pytest.mark.parametrize("join_type", [
    "inner", "left_outer", "right_outer", "full_outer",
    "left_semi", "left_anti", "right_semi", "right_anti",
])
def test_fused_pool_changelog_equivalent_to_dense(join_type):
    """Property: on append-only inputs the fused pool path emits a
    changelog that folds to EXACTLY the dense bucket path's, for every
    join type, including hot-key skew and windowed burst drains (the
    pool runs out_capacity=32 so amplified chunks span many windows)."""
    script = _append_script(seed=11)
    jd = _executor("dense", join_type, out_capacity=4096)
    jp = _executor("pool", join_type, out_capacity=32)
    sd, sp = jd.init_state(), jp.init_state()
    acc_d, acc_p = Counter(), Counter()
    for side, rows, ops in script:
        schema = L if side == "left" else R
        chunk = _chunk(schema, rows, ops)
        sd = _drain_all(jd, sd, chunk, side, acc_d)
        sp = _drain_all(jp, sp, chunk, side, acc_p)
        assert +acc_p == +acc_d, f"{join_type} diverged after {side}"
    for s in (sp.left, sp.right):
        assert int(s.overflow) == 0
        assert int(s.inconsistency) == 0
    assert int(sp.emit_overflow) == 0


@pytest.mark.parametrize("join_type", ["left_outer", "left_semi",
                                       "left_anti"])
def test_retraction_cascade_through_pool_build_side(join_type):
    """A retractable DENSE left side joined against a fused-pool right
    side: left deletes cascade pad/semi/anti transitions that gather
    build rows from the pool — the dense/dense run is ground truth."""
    def run(right_storage):
        kw = dict(table_size=256, bucket_cap=64, out_capacity=8,
                  join_type=join_type)
        if right_storage == "pool":
            kw.update(right_storage="pool", right_pool_size=2048)
        j = HashJoinExecutor(L, R, [col("k")], [col("k")], **kw)
        st = j.init_state()
        acc = Counter()
        rng = np.random.default_rng(3)
        live = []
        for step in range(6):
            if step % 2 == 0:  # appends to the pool (right) side
                rows = [(int(rng.integers(0, 5)),
                         int(rng.integers(0, 100))) for _ in range(6)]
                st = _drain_all(j, st, _chunk(R, rows, [0] * 6),
                                "right", acc)
            else:  # inserts AND deletes on the retractable left side
                ins = [(int(rng.integers(0, 5)),
                        int(rng.integers(0, 100))) for _ in range(4)]
                ops = [0] * 4
                rows = list(ins)
                if live:  # retract an earlier row (cascade)
                    rows.append(live.pop(0))
                    ops.append(1)
                live.extend(ins)
                st = _drain_all(j, st, _chunk(L, rows, ops), "left", acc)
        assert int(st.left.inconsistency) == 0
        assert int(st.right.inconsistency) == 0
        return +acc

    assert run("pool") == run("dense")


def test_update_is_one_lookup_or_insert_per_chunk():
    """The acceptance-criterion probe count: tracing the append-only
    pool update compiles EXACTLY ONE lookup_or_insert and ZERO plain
    lookups — the fused probe replaced the key-table + rank-index
    pair."""
    from risingwave_tpu.state.hash_table import (
        PROBE_STATS,
        reset_probe_stats,
    )

    j = _executor("pool", "inner", out_capacity=64)
    st = j.init_state()
    chunk = _chunk(L, [(1, 10), (1, 11), (2, 20)], [0, 0, 0])
    reset_probe_stats()
    jax.eval_shape(
        lambda s, c: j._update_side_pool(s, c, j.left_keys, None),
        st.left, chunk,
    )
    assert PROBE_STATS == {"lookup": 0, "lookup_or_insert": 1}


def test_bump_allocator_positions_are_contiguous():
    """Accepted inserts take consecutive pool positions per chunk (the
    locality contract) and the cursor advances by exactly the accepted
    count."""
    j = _executor("pool", "inner", out_capacity=64)
    st = j.init_state()
    st, _ = j.apply(st, _chunk(L, [(5, i) for i in range(8)],
                               [0] * 8), "left")
    assert int(st.left.pool_len) == 8
    # every entry's pool position is in [0, 8) and all are distinct
    occ = np.asarray(st.left.table.occupied)
    pos = np.asarray(st.left.pool_pos)[occ]
    assert sorted(pos.tolist()) == list(range(8))
    st, _ = j.apply(st, _chunk(L, [(6, i) for i in range(4)],
                               [0] * 4), "left")
    assert int(st.left.pool_len) == 12


def test_compaction_reclaims_cleaned_pool_rows():
    """After watermark cleaning tombstones most keys, maintenance
    compaction relocates the survivors to a dense prefix, resets the
    bump cursor, and the join still produces exact results."""
    j = HashJoinExecutor(
        L, R, [col("k")], [col("k")],
        table_size=64, out_capacity=64,
        left_storage="pool", right_storage="pool",
        left_pool_size=64, right_pool_size=64,
    )
    j.left_clean = (0, 0, 0)
    st = j.init_state()
    # fill 48/64 of the pool: cursor is past the 3/4 compaction gate
    lrows = [(k, 10 * k + i) for k in range(12) for i in range(4)]
    txt = "I I\n" + "\n".join(f"+ {k} {v}" for k, v in lrows)
    st, _ = j.apply(st, Chunk.from_pretty(txt, names=["k", "a"]), "left")
    assert int(st.left.pool_len) == 48
    st = j.clean_below(st, "left", 0, 10)  # keys 0..9 die (40 rows)
    st = j.maybe_rehash(st)
    assert int(st.left.pool_len) == 8   # compacted to the survivors
    assert int(st.left.table.count()) == 8
    # survivors (keys 10, 11) still join exactly
    st, pending = j.apply_begin(
        st, _chunk(R, [(10, 500), (3, 600)], [0, 0]), "right"
    )
    build = j.build_rows_of(st, "right")
    got = []
    w = 0
    while w == 0 or w * j.out_capacity < int(pending.total):
        got.extend(j.emit_window(
            build, pending, jnp.int32(w), "right")[0].to_rows())
        w += 1
    want = sorted((0, 10, a, 10, 500) for kk, a in lrows if kk == 10)
    assert sorted(got) == want


def test_pool_overflow_is_loud_not_silent():
    """Rows beyond pool capacity surface in the overflow counter and
    never corrupt surviving state."""
    j = HashJoinExecutor(
        L, R, [col("k")], [col("k")],
        table_size=64, out_capacity=64,
        left_storage="pool", right_storage="pool",
        left_pool_size=16, right_pool_size=16,
    )
    st = j.init_state()
    rows = [(k, k) for k in range(24)]  # 24 rows > 16-slot pool
    st, _ = j.apply(st, _chunk(L, rows, [0] * 24), "left")
    assert int(st.left.overflow) == 24 - 16
    assert int(st.left.pool_len) == 16
