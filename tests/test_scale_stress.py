"""Slow wrapper over scripts/scale_stress.py (the ISSUE 7 acceptance
harness), matching the cluster_stress pattern: double then halve the
worker set mid-stream under sustained ingest with concurrent
epoch-pinned reads."""

import pytest


@pytest.mark.slow
def test_scale_stress_short(tmp_path):
    import importlib
    import sys

    sys.path.insert(0, "scripts")
    try:
        ss = importlib.import_module("scale_stress")
    finally:
        sys.path.pop(0)

    summary = ss.run(rounds_per_phase=4, readers=2,
                     bench_rows=16384, data_dir=str(tmp_path))
    assert summary["read_errors"] == 0, summary["read_error_samples"]
    assert summary["ingest_errors"] == 0
    assert not summary["mv_mismatch"]
    # only moved vnodes transferred, both directions minimal
    assert summary["scale_out_minimal"]
    assert summary["scale_in_minimal"]
    # the per-chunk path flowed worker-to-worker, the meta stayed flat
    assert summary["exchange_rows_out"] > 0
    assert summary["exchange_rows_in"] > 0
    assert summary["shuffle_batches_out"] > 0
    assert summary["meta_dml_forwards"] == 0
    assert summary["reads"] > 0
    # Exchange-lite gates (conservative vs the CLI's 1.3 floor: the
    # wrapper's backlog is smaller, so round overheads weigh more):
    # the replicate baseline filtered at the gate, the shuffled path
    # NEVER dropped a gated row and was not slower than replicated
    assert summary["gate_dropped_replicated"] > 0
    assert summary["gate_dropped_shuffled_phase"] == 0
    assert summary["gate_dropped_final_drain"] == 0
    assert summary["shuffle_speedup"] >= 1.0, summary
