"""Workload plane: seeded txgen determinism (in- and cross-process),
TPC-C schema round-trip, exact-full-row retraction DML, the EXISTS
``<>`` decorrelation, and a mini in-process CH run whose views are
byte-identical to a replay of the same seeded transaction log."""

import hashlib
import subprocess
import sys

import pytest

from risingwave_tpu.common.config import RwConfig
from risingwave_tpu.sql import ast
from risingwave_tpu.sql.engine import Engine
from risingwave_tpu.sql.parser import parse
from risingwave_tpu.sql.planner import PlanError
from risingwave_tpu.workload.schema import (RETRACT, TABLES, CHScale,
                                            schema_ddl, table_ddl)
from risingwave_tpu.workload.txgen import TxGen

CONFIG = {
    "streaming": {"chunk_size": 128},
    "state": {"agg_table_size": 1 << 10, "agg_emit_capacity": 256,
              "mv_table_size": 1 << 10, "mv_ring_size": 1 << 12},
}


def _engine() -> Engine:
    return Engine(RwConfig.from_dict(CONFIG))


def _digest(seed: int, n: int) -> str:
    gen = TxGen(seed)
    text = "\n".join(gen.initial_load() + gen.sql_stream(n))
    return hashlib.sha256(text.encode()).hexdigest()


# -- determinism -----------------------------------------------------------

def test_txgen_deterministic_same_seed():
    assert _digest(42, 40) == _digest(42, 40)
    assert _digest(42, 40) != _digest(43, 40)


def test_txgen_deterministic_cross_process():
    """The replay contract: a DIFFERENT process with the same (seed,
    scale) emits the byte-identical statement stream."""
    code = (
        "import hashlib\n"
        "from risingwave_tpu.workload.txgen import TxGen\n"
        "g = TxGen(42)\n"
        "t = '\\n'.join(g.initial_load() + g.sql_stream(40))\n"
        "print(hashlib.sha256(t.encode()).hexdigest())\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        check=True, cwd=".",
    )
    assert out.stdout.strip() == _digest(42, 40)


def test_txgen_mix_and_exact_retractions():
    """Every transaction type appears, and every DELETE retracts a row
    that is LIVE at that point of the stream (the exact-full-row
    contract the marker-tail DML plane depends on)."""
    gen = TxGen(7)
    live: dict[str, dict[tuple, int]] = {t: {} for t in TABLES}
    kinds = {"new_order": 0, "payment": 0, "delivery": 0}
    stmts = list(gen.initial_load())
    for _ in range(300):
        kind, sql = gen.next_transaction()
        kinds[kind] += 1
        stmts.extend(sql)
    def lit(e):
        if isinstance(e, ast.UnaryOp):
            return -lit(e.operand)
        return e.value

    wh_cols = ("w_id", "w_name", "w_tax", "w_ytd")
    for s in stmts:
        (stmt,) = parse(s)
        tab = live[stmt.table]
        if isinstance(stmt, ast.Update):
            # Payment's w_ytd bump rides the UPDATE sugar now — it is
            # an exact-full-row retraction pair in disguise, so its
            # full-pk WHERE must pin exactly one LIVE row
            assert stmt.table == "warehouse"
            pk = lit(stmt.where.right)
            hits = [r for r, n in tab.items() if n > 0 and r[0] == pk]
            assert len(hits) == 1, \
                f"UPDATE pins {len(hits)} live warehouse rows: {pk}"
            (old,) = hits
            tab[old] -= 1
            new = list(old)
            for col, e in stmt.assignments:
                new[wh_cols.index(col)] = lit(e)
            tab[tuple(new)] = tab.get(tuple(new), 0) + 1
            continue
        rows = [tuple(lit(e) for e in r) for r in stmt.rows]
        if isinstance(stmt, ast.Delete):
            for r in rows:
                assert tab.get(r, 0) > 0, \
                    f"DELETE of a non-live row from {stmt.table}: {r}"
                tab[r] -= 1
        else:
            for r in rows:
                tab[r] = tab.get(r, 0) + 1
    assert all(n > 0 for n in kinds.values()), kinds
    assert any(live["order_line"].values())


# -- schema round-trip + retraction DML ------------------------------------

def test_schema_ddl_round_trip():
    eng = _engine()
    for sql in schema_ddl():
        eng.execute(sql)
    for name in TABLES:
        entry = eng.catalog.get(name)
        assert entry.append_only is (not RETRACT[name]), name
        assert name in table_ddl(name)
    # append-only tables refuse DELETE; retractable tables accept it
    eng.execute("INSERT INTO item VALUES (1, 'item-1', 100, 'plain')")
    with pytest.raises(Exception, match="append-only"):
        eng.execute("DELETE FROM item VALUES (1, 'item-1', 100, "
                    "'plain')")


def test_delete_retracts_through_mv():
    eng = _engine()
    eng.execute("CREATE TABLE t (k BIGINT, v BIGINT) "
                "WITH (retract = 'true')")
    eng.execute("CREATE MATERIALIZED VIEW agg AS "
                "SELECT k, count(*) AS n, sum(v) AS s "
                "FROM t GROUP BY k")
    eng.execute("INSERT INTO t VALUES (1, 10), (1, 5), (2, 7)")
    eng.execute("FLUSH")
    assert sorted(tuple(int(x) for x in r)
                  for r in eng.execute("SELECT k, n, s FROM agg")) \
        == [(1, 2, 15), (2, 1, 7)]
    eng.execute("DELETE FROM t VALUES (1, 5)")
    eng.execute("INSERT INTO t VALUES (2, 3)")
    eng.execute("FLUSH")
    assert sorted(tuple(int(x) for x in r)
                  for r in eng.execute("SELECT k, n, s FROM agg")) \
        == [(1, 1, 10), (2, 2, 10)]


# -- EXISTS with a correlated non-equality (the q21 shape) -----------------

Q21_SHAPE = (
    "CREATE MATERIALIZED VIEW w AS "
    "SELECT l1.sk AS sk, count(*) AS n FROM li l1 "
    "WHERE EXISTS (SELECT l2.ok FROM li l2 "
    "WHERE l2.ok = l1.ok AND l2.sk <> l1.sk) "
    "GROUP BY l1.sk"
)


def test_exists_nonequality_plans():
    """The min/max decorrelation accepts ONE correlated ``<>``
    conjunct (plans a grouped join, no PlanError) and still refuses
    shapes it cannot decorrelate."""
    eng = _engine()
    eng.execute("CREATE TABLE li (ok BIGINT, sk BIGINT)")
    rows = eng.execute("EXPLAIN " + Q21_SHAPE)
    text = "\n".join(r[0] for r in rows)
    assert "join" in text.lower()
    with pytest.raises(PlanError):
        eng.execute(
            "EXPLAIN CREATE MATERIALIZED VIEW bad AS "
            "SELECT l1.sk FROM li l1 "
            "WHERE EXISTS (SELECT l2.ok FROM li l2 "
            "WHERE l2.ok = l1.ok AND l2.sk <> l1.sk "
            "AND l2.ok <> l1.sk)")


@pytest.mark.slow
def test_exists_nonequality_executes():
    """End-to-end q21 shape vs brute force, including retraction of
    previously-qualifying rows."""
    eng = _engine()
    eng.execute("CREATE TABLE li (ok BIGINT, sk BIGINT) "
                "WITH (retract = 'true')")
    eng.execute(Q21_SHAPE)
    data = [(o, s) for o in range(1, 9) for s in range(o % 3 + 1)]
    eng.execute("INSERT INTO li VALUES "
                + ", ".join(f"({o}, {s})" for o, s in data))
    eng.execute("DELETE FROM li VALUES (2, 1)")
    data.remove((2, 1))
    eng.execute("FLUSH")

    def brute():
        out: dict[int, int] = {}
        for o1, s1 in data:
            if any(o2 == o1 and s2 != s1 for o2, s2 in data):
                out[s1] = out.get(s1, 0) + 1
        return sorted(out.items())

    got = sorted(tuple(int(x) for x in r)
                 for r in eng.execute("SELECT sk, n FROM w"))
    assert got == brute()


# -- mini CH run: byte identity vs replay ----------------------------------

def test_mini_ch_byte_identity():
    """A small single-node CH run (ch_q1 over the live order_line
    stream, through NewOrder/Delivery retractions) must be
    byte-identical to a fresh engine replaying the same recorded
    statement stream, and must equal the generator's shadow state."""
    from risingwave_tpu.workload.queries import CH_QUERIES, CH_READS

    ch_q1 = dict(CH_QUERIES)["ch_q1"]
    scale = CHScale(warehouses=1, districts_per_w=2, customers_per_d=4,
                    items=8, suppliers=4, nations=2, regions=2,
                    max_lines=3)
    gen = TxGen(11, scale)
    log = [*schema_ddl(), ch_q1, *gen.initial_load()]
    for _ in range(30):
        log.extend(gen.next_transaction()[1])

    eng = _engine()
    for sql in log:
        eng.execute(sql)
    eng.execute("FLUSH")
    got = sorted(tuple(int(x) for x in r)
                 for r in eng.execute(CH_READS["ch_q1"]))

    # the generator's shadow state IS the oracle
    shadow: dict[int, list[int]] = {}
    for lines in gen.order_lines.values():
        for ln in lines:
            a = shadow.setdefault(ln[3], [0, 0, 0])
            a[0] += ln[7]
            a[1] += ln[8]
            a[2] += 1
    want = sorted((n, q, amt, cnt)
                  for n, (q, amt, cnt) in shadow.items())
    assert got == want

    # replay: a second engine applying the same bytes converges to
    # the same bytes
    eng2 = _engine()
    for sql in log:
        eng2.execute(sql)
    eng2.execute("FLUSH")
    got2 = sorted(tuple(int(x) for x in r)
                  for r in eng2.execute(CH_READS["ch_q1"]))
    assert got2 == got
