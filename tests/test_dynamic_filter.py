"""DynamicFilter: stream filtered by a moving scalar (band emission)."""

from collections import Counter

from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.stream.dynamic_filter import DynamicFilterExecutor

L = Schema.of(("k", DataType.INT64), ("v", DataType.INT64))
R = Schema.of(("thr", DataType.INT64))


def _lc(text):
    return Chunk.from_pretty(text, names=["k", "v"])


def _rc(text):
    return Chunk.from_pretty(text, names=["thr"])


def _fold(mv, out):
    for op, *vals in out.to_rows():
        mv[tuple(vals)] += 1 if op in (0, 3) else -1
    return +mv


def test_dynamic_filter_band_emission():
    f = DynamicFilterExecutor(L, filter_col=1, cmp="gt", pool_size=64)
    st = f.init_state()

    # rows arrive before any threshold: stored, nothing emitted
    st, out = f.apply(st, _lc("""
        I I
        + 1 10
        + 2 20
        + 3 30
    """), "left")
    assert out.to_rows() == []

    # threshold 15 arrives: rows v > 15 emitted as inserts
    mv = Counter()
    st, out = f.apply(st, _rc("""
        I
        + 15
    """), "right")
    mv = _fold(mv, out)
    assert mv == Counter({(2, 20): 1, (3, 30): 1})

    # threshold rises to 25: the band (15, 25] is retracted
    st, out = f.apply(st, _rc("""
        I
        U- 15
        U+ 25
    """), "right")
    mv = _fold(mv, out)
    assert mv == Counter({(3, 30): 1})

    # threshold drops to 5: band (5, 25] re-emitted
    st, out = f.apply(st, _rc("""
        I
        U- 25
        U+ 5
    """), "right")
    mv = _fold(mv, out)
    assert mv == Counter({(1, 10): 1, (2, 20): 1, (3, 30): 1})

    # new left rows flow through against the current threshold
    st, out = f.apply(st, _lc("""
        I I
        + 4 3
        + 5 50
    """), "left")
    mv = _fold(mv, out)
    assert mv == Counter({(1, 10): 1, (2, 20): 1, (3, 30): 1, (5, 50): 1})

    # left retraction of a passing row
    st, out = f.apply(st, _lc("""
        I I
        - 2 20
    """), "left")
    mv = _fold(mv, out)
    assert mv == Counter({(1, 10): 1, (3, 30): 1, (5, 50): 1})
    assert int(st.inconsistency) == 0 and int(st.overflow) == 0


def test_dynamic_filter_rhs_emptied_retracts_all():
    f = DynamicFilterExecutor(L, filter_col=1, cmp="gt", pool_size=64)
    st = f.init_state()
    st, _ = f.apply(st, _lc("""
        I I
        + 1 50
    """), "left")
    mv = Counter()
    st, out = f.apply(st, _rc("""
        I
        + 10
    """), "right")
    mv = _fold(mv, out)
    assert mv == Counter({(1, 50): 1})
    # the RHS 1-row aggregate becomes empty: everything retracts
    st, out = f.apply(st, _rc("""
        I
        - 10
    """), "right")
    mv = _fold(mv, out)
    assert mv == Counter()
    # new left rows don't pass while the RHS is empty
    st, out = f.apply(st, _lc("""
        I I
        + 2 99
    """), "left")
    assert out.to_rows() == []


def test_dynamic_filter_inchunk_annihilation():
    f = DynamicFilterExecutor(L, filter_col=1, cmp="gt", pool_size=64)
    st = f.init_state()
    st, _ = f.apply(st, _lc("""
        I I
        + 1 50
        - 1 50
    """), "left")
    assert int(st.inconsistency) == 0
    # threshold drop must NOT resurrect the annihilated row
    st, out = f.apply(st, _rc("""
        I
        + 0
    """), "right")
    assert out.to_rows() == []
