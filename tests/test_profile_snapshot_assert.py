"""Slow wrapper: the incremental-snapshot regression gate.

Runs ``scripts/profile_snapshot.py --assert --small`` as the bench
drivers do, so a dirty-block-scaling, sync-readback, unbounded-queue,
or recovery-equivalence regression fails CI loudly (ISSUE 4 acceptance
gate; mirrors test_profile_q8_assert)."""

import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_profile_snapshot_assert_small():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(root, "scripts",
                                      "profile_snapshot.py"),
         "--assert", "--small"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=1800, cwd=root,
    )
    assert out.returncode == 0, \
        f"profile_snapshot gate failed:\n{out.stdout}\n{out.stderr}"
    assert "profile_snapshot --assert: OK" in out.stdout
