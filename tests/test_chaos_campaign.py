"""Slow wrapper over scripts/chaos_campaign.py (the ISSUE 6 acceptance
harness): one seeded schedule end-to-end against a real 4-role
multi-process cluster.  The full ≥3-schedule campaign runs standalone:

    python scripts/chaos_campaign.py --assert
"""

import pytest


def _run(schedule: str, data_dir: str, **kw) -> dict:
    import importlib
    import sys

    sys.path.insert(0, "scripts")
    try:
        cc = importlib.import_module("chaos_campaign")
    finally:
        sys.path.pop(0)
    return cc.run_schedule(schedule, data_dir=data_dir, **kw)


@pytest.mark.slow
def test_corruption_storm(tmp_path):
    """Integrity-lite acceptance: seeded bit_flip/truncate corruption
    on the workers' MV-export and checkpoint uploads, with serving
    reads, the compactor and the meta scrubber live — every planted
    corruption detected (quarantine note per corrupted object), every
    reachable one repaired, 0 client-visible read errors, 0 silent
    wrong reads (byte-identical convergence vs single node)."""
    summary = _run("corruption_storm", str(tmp_path), rounds=8)
    assert summary["ok"], summary
    assert summary["corruptions_planted"], summary
    assert summary["all_corruptions_detected"], summary
    assert summary["scrub_unrepaired"] == 0, summary
    assert summary["read_errors"] == 0, summary["read_error_samples"]
    assert summary["mv_mismatches"] == 0
    assert summary["rounds_committed"] >= summary["rounds"]


@pytest.mark.slow
def test_chaos_campaign_meta_kill(tmp_path):
    """Meta SIGKILL + restart mid-round: recovery from the durable
    MetaStore/manifest, worker + serving re-registration via backoff,
    0 read errors, 0 stuck rounds, byte-identical convergence."""
    summary = _run("meta_kill", str(tmp_path), rounds=8,
                   kill_at_round=3)
    assert summary["ok"], summary
    assert summary["meta_recovered"] is True
    assert summary["read_errors"] == 0, summary["read_error_samples"]
    assert summary["rounds_committed"] >= summary["rounds"]
    assert summary["mv_mismatches"] == 0
    assert summary["worker_registrations"] >= 4  # 2 workers × 2


@pytest.mark.slow
def test_shuffle_storm(tmp_path):
    """Exchange-lite acceptance: seeded drops + a one-way
    worker1>worker2 partition on the SLICED exchange seam during
    partitioned-JOIN ingest with mid-stream retraction churn — lost
    sliced batches heal through the fence completeness audit, reads
    stay zero-error, and the join MV converges byte-identical to a
    single node."""
    summary = _run("shuffle_storm", str(tmp_path), rounds=6)
    assert summary["ok"], summary
    assert summary["read_errors"] == 0, summary["read_error_samples"]
    assert summary["mv_mismatches"] == 0
    assert summary["faults_injected"] > 0
    assert summary["exchange_faults_absorbed"] > 0
    assert sorted(summary["shuffled_tables"]) == ["a", "b"]
    assert summary["partitions"] == 2
