"""Exchange-lite: the cluster shuffle plane (ISSUE 11).

- the host-side hash twin is bit-identical to the device hash (the
  property every slicing/filter/gate agreement rests on);
- ExchangePlanner compiles a deterministic, JSON-round-trippable
  choreography (shuffle vs replicate per table, standby, slices);
- route_batch slices one batch per peer (owned rows + the leader's
  slice to the standby), positions elided and re-derived exactly;
- sparse histories: global positions, idempotent redelivery,
  hole-fill, gap refusal, ownership completeness audit;
- the reader-side vnode filter packs chunks with owned rows only and
  the VnodeGate state carries a zero drop counter on that path.
"""

from __future__ import annotations

import numpy as np
import pytest

from risingwave_tpu.cluster.exchange import (
    Choreography,
    ExchangePlanner,
    ShuffleService,
    vnodes_of_rows,
)
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.connector.dml import TableDmlManager

N_VN = 16
SCHEMA = Schema((Field("k", DataType.INT64, nullable=False),
                 Field("v", DataType.INT64, nullable=False)))


def _jobs(owners1, owners2):
    return [{"name": "agg", "dml_tables": ["t"],
             "shuffle_cols": {"t": 0}, "kinds": {"t": "source"},
             "owners": {1: list(owners1), 2: list(owners2)}}]


def test_host_hash_matches_device_hash():
    import jax.numpy as jnp

    from risingwave_tpu.common.hash import (
        hash64_columns,
        hash64_i64_host,
    )

    vals = np.concatenate([
        np.arange(-1000, 1000, dtype=np.int64),
        np.array([0, 1, -1, 2**62, -(2**62), 123456789012345],
                 np.int64),
    ])
    dev = np.asarray(hash64_columns([jnp.asarray(vals)]))
    host = hash64_i64_host(vals)
    assert (dev == host).all()


def test_planner_compiles_and_roundtrips():
    ch = ExchangePlanner.compile(
        _jobs(range(0, 8), range(8, 16)), N_VN, version=5)
    t = ch.tables["t"]
    assert t["mode"] == "shuffle" and t["key_col"] == 0
    assert t["leader"] == 1 and t["standby"] == 2
    assert t["slices"][1] == list(range(0, 8))
    assert [s.edge for s in ch.specs] == ["src:t>agg"]
    # JSON round trip is exact (the routing-push wire format)
    ch2 = Choreography.from_doc(ch.to_doc())
    assert ch2.to_doc() == ch.to_doc()
    # untraceable key → the edge degrades to replicate
    jobs = _jobs(range(0, 8), range(8, 16))
    jobs[0]["shuffle_cols"] = {}
    ch3 = ExchangePlanner.compile(jobs, N_VN)
    assert ch3.tables["t"]["mode"] == "replicate"
    # disagreeing consumers degrade too
    jobs = _jobs(range(0, 8), range(8, 16)) + [{
        "name": "j2", "dml_tables": ["t"], "shuffle_cols": {"t": 1},
        "kinds": {"t": "join"}, "owners": {1: [0], 2: [1]},
    }]
    ch4 = ExchangePlanner.compile(jobs, N_VN)
    assert ch4.tables["t"]["mode"] == "replicate"


def test_route_batch_slices_and_unpacks_exactly():
    ch = ExchangePlanner.compile(
        _jobs(range(0, 8), range(8, 16)), N_VN, version=1)
    svc = ShuffleService(worker_id=1)
    svc.update(ch)
    rows = [(i % 11, i * 10) for i in range(40)]
    vns = vnodes_of_rows(rows, 0, N_VN)
    out = svc.route_batch("t", 100, rows)
    assert set(out) == {2}
    payload = out[2]
    # the standby carries ITS slice plus the LEADER's slice (= all)
    items = ShuffleService.unpack_rows(payload)
    assert items == [(100 + i, rows[i]) for i in range(40)]
    # a non-standby peer gets only its owned slice
    ch3 = ExchangePlanner.compile(
        [{"name": "agg", "dml_tables": ["t"], "shuffle_cols": {"t": 0},
          "kinds": {"t": "source"},
          "owners": {1: list(range(0, 6)), 2: list(range(6, 11)),
                     3: list(range(11, 16))}}], N_VN, version=2)
    svc.update(ch3)
    out = svc.route_batch("t", 0, rows)
    got3 = ShuffleService.unpack_rows(out[3])
    assert got3 == [(i, rows[i]) for i in range(40)
                    if vns[i] in range(11, 16)]


def test_sparse_history_positions_and_repair():
    ch = ExchangePlanner.compile(
        _jobs(range(0, 8), range(8, 16)), N_VN, version=1)
    svc = ShuffleService(worker_id=1)
    svc.update(ch)
    lead = TableDmlManager(SCHEMA)
    rows = [(i % 11, i * 10) for i in range(30)]
    lead.insert(rows)
    vns = vnodes_of_rows(rows, 0, N_VN)
    own2 = set(range(8, 16))

    fol = TableDmlManager(SCHEMA)
    payload = svc.route_batch("t", 0, rows)[2]
    # deliver only the follower's OWN slice (drop the standby extra)
    items = [(p, r) for p, r in ShuffleService.unpack_rows(payload)
             if vns[p] in own2]
    n = fol.insert_sparse(0, 30, items, vns)
    assert fol.history_len() == 30
    assert n == sum(1 for v in vns if v in own2)
    # global positions preserved; non-owned are placeholders
    assert fol.missing_positions(own2, 0, 30) == []
    missing1 = fol.missing_positions(set(range(0, 8)), 0, 30)
    assert missing1 == [p for p in range(30) if vns[p] not in own2]
    # idempotent redelivery + hole fill from the full payload
    n2 = ShuffleService.apply_batch(fol, payload)
    assert n2 == len(missing1)
    assert fol.missing_positions(set(range(16)), 0, 30) == []
    # a gap is refused (fence repair fetches first)
    with pytest.raises(ValueError, match="gap"):
        fol.insert_sparse(99, 101, [(99, (1, 1))], [])
    # leader-side repair slicing re-cuts any range for any vnode set
    sl = svc.slice_history(lead, 5, None, own2, "t")
    assert sl["seq"] == 5 and sl["end"] == 30
    assert [p for p, _ in sl["items"]] == \
        [p for p in range(5, 30) if vns[p] in own2]


def test_reader_filter_packs_owned_rows_and_gate_stays_clean():
    import jax.numpy as jnp

    from risingwave_tpu.cluster.scale.gate import VnodeGateExecutor
    from risingwave_tpu.expr.node import InputRef

    lead = TableDmlManager(SCHEMA)
    rows = [(i % 11, i * 10) for i in range(50)]
    lead.insert(rows)
    vns = vnodes_of_rows(rows, 0, N_VN)
    own = frozenset(range(0, 8))
    r = lead.new_reader(8)
    r.vnode_filter = (0, own, N_VN)
    gate = VnodeGateExecutor(SCHEMA, [InputRef(0)], N_VN)
    state = (gate.make_mask(own), jnp.zeros((), jnp.int64))
    got = []
    while r.pending():
        chunk = r.next_chunk()
        state, out = gate.apply(state, chunk)
        vis = np.nonzero(np.asarray(chunk.valid))[0]
        got += [(int(np.asarray(chunk.columns[0])[i]),
                 int(np.asarray(chunk.columns[1])[i])) for i in vis]
    want = [rows[i] for i in range(50) if vns[i] in own]
    assert got == want
    assert r.offset == 50  # cursor is GLOBAL (ends on the fence)
    assert r.filtered_rows == 50 - len(want)
    # the gate audited every row as owned: ZERO drops
    assert int(np.asarray(state[1])) == 0
    # without the filter, the gate does the dropping (and counts it)
    r2 = lead.new_reader(8)
    state2 = (gate.make_mask(own), jnp.zeros((), jnp.int64))
    while r2.pending():
        state2, _ = gate.apply(state2, r2.next_chunk())
    assert int(np.asarray(state2[1])) == 50 - len(want)


def test_vn64_packing_roundtrip():
    from risingwave_tpu.cluster.exchange.shuffle import (
        pack_vnodes,
        unpack_vnodes,
    )

    vns = [i % N_VN for i in range(257)]
    assert unpack_vnodes({"vn64": pack_vnodes(vns)}) == vns
    assert unpack_vnodes({"vnodes": vns}) == vns
