"""Watermark generation, late-row filtering, EOWC, state cleaning."""

from collections import Counter

import numpy as np

from risingwave_tpu.common.chunk import Chunk
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.expr.agg import count_star
from risingwave_tpu.expr.node import col
from risingwave_tpu.stream.fragment import Fragment
from risingwave_tpu.stream.hash_agg import HashAggExecutor
from risingwave_tpu.stream.runtime import StreamingJob
from risingwave_tpu.stream.watermark import (
    EowcSortExecutor,
    WatermarkFilterExecutor,
)

S = Schema.of(("ts", DataType.INT64), ("v", DataType.INT64))


def _chunk(text):
    return Chunk.from_pretty(text, names=["ts", "v"])


class ListSource:
    def __init__(self, chunks):
        self.chunks = list(chunks)
        self.offset = 0

    def next_chunk(self):
        c = self.chunks[self.offset % len(self.chunks)]
        self.offset += 1
        return c


def test_watermark_filter_drops_late_rows():
    wf = WatermarkFilterExecutor(S, ts_col=0, delay_us=10)
    frag = Fragment([wf])
    st = frag.init_states()
    st, out = frag.step(st, _chunk("""
        I I
        + 100 1
        + 200 2
    """))
    assert len(out.to_rows()) == 2
    assert wf.current_watermark(st[0]) == 190
    # ts=150 is late (wm=190), ts=195 is within allowance
    st, out = frag.step(st, _chunk("""
        I I
        + 150 3
        + 195 4
    """))
    assert [r[2] for r in out.to_rows()] == [4]
    assert int(st[0].late_rows) == 1


def test_eowc_sort_emits_in_order():
    from risingwave_tpu.stream.message import Watermark

    eowc = EowcSortExecutor(S, ts_col=0, pool_size=32, emit_capacity=16)
    frag = Fragment([eowc])
    st = frag.init_states()
    st, _ = frag.step(st, _chunk("""
        I I
        + 300 3
        + 100 1
        + 200 2
    """))
    st, outs = frag.flush(st, 1)
    assert outs[0].to_rows() == []  # no watermark yet

    st = frag.on_watermark(st, Watermark(0, 250))
    st, outs = frag.flush(st, 2)
    assert [r[1] for r in outs[0].to_rows()] == [100, 200]  # sorted, closed
    st = frag.on_watermark(st, Watermark(0, 1000))
    st, outs = frag.flush(st, 3)
    assert [r[1] for r in outs[0].to_rows()] == [300]


def test_windowed_agg_state_cleaning_end_to_end():
    """watermark filter -> windowed count; closed windows are evicted."""
    window = 100
    wf = WatermarkFilterExecutor(S, ts_col=0, delay_us=0)
    agg = HashAggExecutor(
        S, [("w", col("ts") - (col("ts") % window))], [count_star("n")],
        table_size=64, emit_capacity=16,
        watermark_group_idx=0, watermark_lag=window,
    )
    frag = Fragment([wf, agg])
    job = StreamingJob(
        ListSource([
            _chunk("""
                I I
                + 100 1
                + 110 1
            """),
            _chunk("""
                I I
                + 450 1
            """),
        ]),
        frag,
    )
    job.run(barriers=2, chunks_per_barrier=1)
    # wm=450 after 2nd barrier: window 100 (closes at 200) evicted
    occupied = np.asarray(job.states[1].table.occupied)
    keys = np.asarray(job.states[1].table.key_cols[0])
    live = sorted(int(k) for k, o in zip(keys, occupied) if o)
    assert live == [400]


def test_eowc_emits_at_the_closing_barrier():
    """Regression: rows closed by THIS barrier's watermark emit now."""
    from risingwave_tpu.stream.materialize import AppendOnlyMaterialize

    wf = WatermarkFilterExecutor(S, ts_col=0, delay_us=0)
    eowc = EowcSortExecutor(S, ts_col=0, pool_size=32, emit_capacity=16)
    mv = AppendOnlyMaterialize(S, ring_size=64)
    job = StreamingJob(
        ListSource([
            _chunk("""
                I I
                + 100 1
                + 300 3
            """),
        ]),
        Fragment([wf, eowc, mv]),
    )
    job.run(barriers=1, chunks_per_barrier=1)
    # wm = 300 at the first barrier: ts=100 is closed and must be in
    # the MV already (not waiting for a second barrier)
    rows = mv.to_host(job.states[2])
    assert [r[0] for r in rows] == [100]
