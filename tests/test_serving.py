"""Serve-lite: the engine-free serving tier (ISSUE 5 tentpole).

A ServingWorker reads MV rows straight from shared Hummock SSTs at a
meta-pinned epoch — no Engine on the read path (the subprocess
jax-free contract is asserted in test_chaos.py; here in-process
replicas cover routing, leases, churn, and byte-identity vs the
owning worker's ``storage_serve_mv``)."""

import pickle
import threading
import time

import pytest

from risingwave_tpu.cluster import ComputeWorker, MetaService
from risingwave_tpu.common.config import RwConfig
from risingwave_tpu.serve import ServingWorker
from risingwave_tpu.serve.worker import ServeUnsupported, plan_read


def _cfg():
    return RwConfig.from_dict({
        "streaming": {"chunk_size": 128},
        "state": {"agg_table_size": 512, "agg_emit_capacity": 128,
                  "mv_table_size": 512, "mv_ring_size": 1024},
        "storage": {"checkpoint_keep_epochs": 4},
    })


def _rows(served):
    return sorted(tuple(r) for r in served[1])


_DDL = [
    "CREATE SOURCE t (k BIGINT, v BIGINT) "
    "WITH (connector='datagen')",
    "CREATE MATERIALIZED VIEW m1 AS "
    "SELECT k % 8 AS g, count(*) AS n FROM t GROUP BY k % 8",
]


def _mk_cluster(tmp_path, ddl=_DDL, rounds=3):
    meta = MetaService(str(tmp_path), heartbeat_timeout_s=5.0)
    meta.start(port=0, monitor=False, compactor=False)
    addr = f"127.0.0.1:{meta.rpc_port}"
    w = ComputeWorker(addr, str(tmp_path), config=_cfg(),
                      heartbeat_interval_s=0.5).start()
    for sql in ddl:
        meta.execute_ddl(sql)
    for _ in range(rounds):
        assert meta.tick(1)["committed"]
    return meta, addr, w


# -- byte identity vs the owning engine's storage read -------------------
def test_sst_view_byte_identical_to_storage_serve_mv(tmp_path):
    """A standalone SstView over the same data_dir returns the EXACT
    payload bytes Engine.storage_serve_mv decodes — the acceptance
    byte-identity surface."""
    from risingwave_tpu.sql import Engine

    eng = Engine(_cfg(), data_dir=str(tmp_path))
    for sql in _DDL:
        eng.execute(sql)
    eng.tick(barriers=2, chunks_per_barrier=1)
    eng.storage_export_mv("m1")
    want_rows = eng.storage_serve_mv("m1")
    assert len(want_rows) == 8

    sv = ServingWorker(None, str(tmp_path))
    sv.start()  # standalone: follows the manifest, no meta lease
    try:
        raw = sv.view.scan_mv("m1")
        assert [pickle.loads(v) for v in raw] \
            == [tuple(r) for r in want_rows]
        assert raw == [pickle.dumps(tuple(r), protocol=4)
                       for r in want_rows]
        # the SELECT surface agrees with the raw payloads
        cols, rows, _ = sv.read("SELECT g, n FROM m1")
        assert cols == ["g", "n"]
        assert sorted(rows) == sorted(
            (r[0], r[1]) for r in want_rows
        )
        # point get goes through the bloom/key-range pruned path
        _, rows, _ = sv.read("SELECT n FROM m1 WHERE g = 5")
        assert rows == [(want_rows[5][1],)] or len(rows) == 1
    finally:
        sv.stop()


# -- read planning (unit) ------------------------------------------------
def test_plan_read_shapes():
    from risingwave_tpu.serve.reader import MvSchema
    from risingwave_tpu.sql import ast
    from risingwave_tpu.sql.parser import parse

    schema = MvSchema({
        "mv": "m",
        "columns": [
            {"name": "a", "kind": "int", "scale": 0, "hidden": False},
            {"name": "b", "kind": "int", "scale": 0, "hidden": False},
            {"name": "_hidden_sk", "kind": "int", "scale": 0,
             "hidden": True},
        ],
        "pk": [0, 1],
    })

    def plan(sql):
        (sel,) = parse(sql)
        assert isinstance(sel, ast.Select)
        return plan_read(sel, schema)

    p = plan("SELECT * FROM m")
    assert p.mode == "scan" and p.cols == [0, 1]  # hidden excluded

    p = plan("SELECT b, a FROM m WHERE a = 3 AND b = 4")
    assert p.mode == "get" and p.cols == [1, 0]

    p = plan("SELECT a FROM m WHERE a >= 2 AND a < 7 LIMIT 5")
    assert p.mode == "scan" and p.limit == 5
    assert p.lo > b"m:m\x00" and p.hi is not None

    # flipped literal-first comparison normalizes
    p2 = plan("SELECT a FROM m WHERE 2 <= a AND 7 > a LIMIT 5")
    assert (p2.lo, p2.hi) == (p.lo, p.hi)

    # ORDER BY pushdown: scan order already IS memcomparable-pk order,
    # so an ascending pk prefix is a no-op the replica accepts
    p = plan("SELECT a, b FROM m ORDER BY a LIMIT 3")
    assert p.mode == "scan" and p.limit == 3
    p = plan("SELECT a, b FROM m ORDER BY a, b LIMIT 3 OFFSET 1")
    assert p.mode == "scan" and p.limit == 3 and p.offset == 1
    p = plan("SELECT b FROM m WHERE a >= 2 ORDER BY a")
    assert p.mode == "scan" and p.lo > b"m:m\x00"

    # non-leading pk compares ride as RESIDUAL filters on a scan
    # (Exchange-lite round: composite predicates stop bouncing to the
    # owning worker)
    p = plan("SELECT a FROM m WHERE b = 1")
    assert p.mode == "scan" and p.residual == [(1, "equal", 1)]
    p = plan("SELECT a FROM m WHERE a >= 2 AND b < 4")
    assert p.mode == "scan" and p.lo > b"m:m\x00"
    assert p.residual == [(1, "less_than", 4)]

    for bad in [
        "SELECT count(*) FROM m",                  # aggregate
        "SELECT a FROM m GROUP BY a",              # group by
        "SELECT a FROM m ORDER BY a DESC",         # descending
        "SELECT a FROM m ORDER BY b",              # not a pk PREFIX
        "SELECT a FROM m ORDER BY a, b, a",        # beyond the pk
        "SELECT a FROM m ORDER BY a + 1",          # expression key
        "SELECT a + 1 FROM m",                     # expression
        "SELECT a FROM m WHERE a + 1 = 2",         # computed predicate
    ]:
        with pytest.raises(ServeUnsupported):
            plan(bad)

    # unknown column is a FINAL error, not a fallback
    with pytest.raises(ValueError, match="does not exist"):
        plan("SELECT nope FROM m")


# -- cluster routing -----------------------------------------------------
def test_cluster_serving_routes_point_range_and_fallback(tmp_path):
    """SELECTs route to the replica (round-robin of one), pinned at
    the last cluster-committed epoch; engine-only shapes fall back to
    the owning worker; the replica follows commits forward."""
    meta, addr, w = _mk_cluster(tmp_path)
    sv = ServingWorker(addr, str(tmp_path),
                       heartbeat_interval_s=0.2).start()
    try:
        assert _rows(meta.serve("SELECT g, n FROM m1")) == [
            (g, 48) for g in range(8)
        ]
        assert sv.reads_total == 1  # the read came from the replica
        assert _rows(meta.serve("SELECT g, n FROM m1 WHERE g = 3")) \
            == [(3, 48)]
        assert _rows(meta.serve(
            "SELECT g, n FROM m1 WHERE g >= 2 AND g < 5"
        )) == [(g, 48) for g in (2, 3, 4)]
        # engine-only shape: replica refuses, owner serves
        assert _rows(meta.serve("SELECT count(*) FROM m1"))[0][0] == 8
        assert sv.read_errors == 0
        # commits advance; the next routed read sees the new epoch
        for _ in range(2):
            assert meta.tick(1)["committed"]
        assert _rows(meta.serve("SELECT g, n FROM m1")) == [
            (g, 80) for g in range(8)
        ]
        assert meta.metrics.get("cluster_serving_reads_total") >= 4
        assert meta.state()["serving"][0]["granted_vid"] \
            >= sv.view.version.vid
    finally:
        sv.stop()
        w.stop()
        meta.stop()


def test_serving_replica_death_zero_errors(tmp_path):
    """Reads keep answering while the only replica dies mid-stream
    (fallback to the owning worker), and the dead replica's pin lease
    is reaped so vacuum is never blocked forever."""
    meta, addr, w = _mk_cluster(tmp_path)
    meta.heartbeat_timeout_s = 0.5
    sv = ServingWorker(addr, str(tmp_path),
                       heartbeat_interval_s=0.1).start()
    stop = threading.Event()
    errors: list = []

    def read_loop():
        while not stop.is_set():
            try:
                got = _rows(meta.serve("SELECT g, n FROM m1"))
                assert got and all(len(r) == 2 for r in got)
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))
            time.sleep(0.01)

    reader = threading.Thread(target=read_loop, daemon=True)
    reader.start()
    try:
        time.sleep(0.3)
        # hard-kill the replica: no unregister, sockets just die
        sv._stop.set()
        sv._server.stop()
        sv._server = None
        time.sleep(0.3)
        assert _rows(meta.serve("SELECT g, n FROM m1")) == [
            (g, 48) for g in range(8)
        ]
        # the stale lease is reaped once heartbeats expire
        deadline = time.monotonic() + 10
        while meta.state()["serving"]:
            meta.check_heartbeats()
            assert time.monotonic() < deadline, "lease never reaped"
            time.sleep(0.1)
        assert meta.versions.pinned_count() == 0
    finally:
        stop.set()
        reader.join(timeout=5)
        sv.stop()
        w.stop()
        meta.stop()
    assert errors == [], errors[:3]


def test_serving_reads_under_compaction_and_vacuum(tmp_path):
    """Churn: reads concurrent with ingest rounds, compaction, and
    vacuum — 0 read errors, results always a committed-round multiple,
    final rows byte-identical to the owning worker's, and vacuum never
    deletes an SST under the replica's lease (errors would surface as
    ObjectError reads)."""
    meta, addr, w = _mk_cluster(tmp_path)
    sv = ServingWorker(addr, str(tmp_path),
                       heartbeat_interval_s=0.05).start()
    stop = threading.Event()
    errors: list = []
    served = [0]

    def read_loop():
        while not stop.is_set():
            try:
                got = _rows(meta.serve("SELECT g, n FROM m1"))
                assert len(got) == 8
                # every read is one committed round's worth of rows
                counts = {n for _, n in got}
                assert len(counts) == 1 and next(iter(counts)) % 16 == 0
                served[0] += 1
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))
            time.sleep(0.005)

    reader = threading.Thread(target=read_loop, daemon=True)
    reader.start()
    try:
        for _ in range(6):
            assert meta.tick(1)["committed"]
            meta.hummock.compact_once()
            meta.storage_vacuum()
        stop.set()
        reader.join(timeout=10)
        assert errors == [], errors[:3]
        assert served[0] > 0
        assert sv.read_errors == 0
        # quiesced: replica rows byte-identical to the owning worker
        want = _rows(meta.serve("SELECT count(*) FROM m1"))  # owner
        assert want[0][0] == 8
        cols, rows, _ = sv.read(
            "SELECT g, n FROM m1",
            min_epoch=meta.versions.max_committed_epoch,
        )
        owner_rows = _rows(meta.serve("SELECT g, n FROM m1"))
        assert sorted(rows) == owner_rows == [
            (g, 144) for g in range(8)
        ]
        # GC actually ran under the churn
        assert meta.metrics.get("storage_gc_objects_total") >= 1
    finally:
        stop.set()
        sv.stop()
        w.stop()
        meta.stop()


def test_serving_mv_on_mv_and_multiple_replicas(tmp_path):
    """Every MV riding a job exports (MV-on-MV included); two replicas
    split the round-robin."""
    ddl = _DDL + [
        "CREATE MATERIALIZED VIEW top1 AS "
        "SELECT g, n FROM m1 WHERE g < 2",
    ]
    meta, addr, w = _mk_cluster(tmp_path, ddl=ddl, rounds=2)
    sv1 = ServingWorker(addr, str(tmp_path),
                        heartbeat_interval_s=0.2).start()
    sv2 = ServingWorker(addr, str(tmp_path),
                        heartbeat_interval_s=0.2).start()
    try:
        for _ in range(4):
            assert _rows(meta.serve("SELECT g, n FROM top1")) == [
                (0, 32), (1, 32)
            ]
        assert sv1.reads_total + sv2.reads_total == 4
        assert sv1.reads_total > 0 and sv2.reads_total > 0
    finally:
        sv1.stop()
        sv2.stop()
        w.stop()
        meta.stop()


def test_corrupt_replica_block_falls_back_zero_errors(tmp_path):
    """Integrity satellite: a replica whose LOCAL reads of a shared
    SST return corrupt bytes (bad disk/cache sector) answers
    ``ServeUnavailable`` — the meta routes the read to the healthy
    replica (or owner) with ZERO client errors and ZERO wrong rows,
    and the corruption is reported for quarantine."""
    import os

    from risingwave_tpu.storage.hummock import (
        LocalFsObjectStore,
        StoreFaults,
    )

    meta, addr, w = _mk_cluster(tmp_path)
    # replica A reads every SST through a corrupting store (bit_flip
    # on get, deterministic); replica B reads the same files clean
    bad_faults = StoreFaults(seed=3)
    bad_faults.fail("get", substr="sst/", mode="bit_flip", times=64)
    bad_store = LocalFsObjectStore(
        os.path.join(str(tmp_path), "hummock"), faults=bad_faults)
    sv_bad = ServingWorker(addr, str(tmp_path), store=bad_store,
                           heartbeat_interval_s=0.2).start()
    sv_ok = ServingWorker(addr, str(tmp_path),
                          heartbeat_interval_s=0.2).start()
    try:
        # every routed read answers correctly regardless of which
        # replica round-robin picks first
        for _ in range(6):
            assert _rows(meta.serve("SELECT g, n FROM m1")) == [
                (g, 48) for g in range(8)
            ]
        # the corrupt replica detected typed corruption (never served
        # a wrong row, never surfaced a client error)
        assert bad_faults.injected_corruptions > 0
        assert sv_bad.metrics.get("integrity_errors_total",
                                  kind="sst_footer") >= 1 \
            or sv_bad.metrics.get("integrity_errors_total",
                                  kind="sst_block") >= 1
        assert sv_bad.read_errors == 0
        # the healthy replica carried reads
        assert sv_ok.reads_total > 0
        # the report reached the meta's integrity pipeline
        deadline = time.monotonic() + 10
        while True:
            try:
                assert meta.metrics.get("integrity_errors_total",
                                        kind="sst_footer") >= 1 \
                    or meta.metrics.get("integrity_errors_total",
                                        kind="sst_block") >= 1
                break
            except (KeyError, AssertionError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)
    finally:
        sv_bad.stop()
        sv_ok.stop()
        w.stop()
        meta.stop()
