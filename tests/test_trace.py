"""Trace-lite (ISSUE 14): span recorder semantics, cross-role trace
assembly, propagation under injected faults, metrics-plane merging,
and DROP-time series retirement."""

import json
import threading

import pytest

from risingwave_tpu.common import faults as faults_mod
from risingwave_tpu.common.metrics import (
    MetricsRegistry,
    merge_prometheus,
)
from risingwave_tpu.common.trace import (
    GLOBAL_TRACE,
    NULL_SPAN,
    SpanRecorder,
    merge_dumps,
    round_ids,
    spans_for_round,
    to_chrome_trace,
    tree_check,
)


@pytest.fixture(autouse=True)
def _clean_globals():
    """Each test gets a clean global recorder and NO fault fabric; both
    are restored so unrelated suites never see leaked state."""
    role, n, cap = (GLOBAL_TRACE.role, GLOBAL_TRACE.sample_n,
                    GLOBAL_TRACE.capacity)
    GLOBAL_TRACE.configure(role="proc", sample_n=1)
    GLOBAL_TRACE.clear()
    faults_mod.install(None)
    yield
    faults_mod.install(None)
    GLOBAL_TRACE.configure(role=role, sample_n=n, capacity=cap)
    GLOBAL_TRACE.clear()


# -- recorder semantics --------------------------------------------------
def test_disabled_tracing_is_the_null_singleton():
    """sample_n=0 is the overhead contract: span() hands back ONE
    shared null object — no allocation, no clock read, empty ring."""
    rec = SpanRecorder(role="w", sample_n=0)
    assert rec.span("round", trace_id="round-1") is NULL_SPAN
    assert rec.sampled_span("read") is NULL_SPAN
    assert rec.activate(("round-1", "w:1")) is NULL_SPAN
    with rec.span("x", trace_id="round-1") as s:
        assert s.set(k=1) is NULL_SPAN and s.ctx is None
    assert rec.dump() == []


def test_span_without_any_context_is_null():
    rec = SpanRecorder(role="w", sample_n=1)
    # enabled, but no active trace, no explicit ctx, no trace_id:
    # nothing to attach to — the chunk path stays allocation-free
    assert rec.span("orphan") is NULL_SPAN
    assert rec.dump() == []


def test_nesting_and_cross_thread_ctx_propagation():
    rec = SpanRecorder(role="meta", sample_n=1)
    with rec.span("round", trace_id="round-7", epoch=7) as root:
        with rec.span("barrier", unit="u0") as b:
            assert b.parent_id == root.span_id
        rctx = root.ctx

        def fan_out():
            # fan-out threads have an empty TLS stack: the explicit
            # ctx= is the only way spans parent correctly
            with rec.span("barrier", ctx=rctx, unit="u1"):
                pass

        t = threading.Thread(target=fan_out)
        t.start()
        t.join()
    spans = rec.dump("round-7")
    assert {s["name"] for s in spans} == {"round", "barrier"}
    chk = tree_check(spans)
    assert chk["complete"] and chk["root_covers"], chk
    parents = {s["parent_id"] for s in spans if s["name"] == "barrier"}
    assert parents == {root.span_id}


def test_ring_is_bounded_flight_recorder():
    rec = SpanRecorder(role="w", sample_n=1, capacity=8)
    for i in range(20):
        with rec.span("s", trace_id="round-1", i=i):
            pass
    spans = rec.dump()
    assert len(spans) == 8
    # oldest fell off, newest survive, order preserved
    assert [s["attrs"]["i"] for s in spans] == list(range(12, 20))


def test_activate_adopts_remote_context():
    """The RPC server seam: a frame's trace key becomes the handler
    thread's context, so handler-side spans parent across processes."""
    rec = SpanRecorder(role="worker1", sample_n=1)
    with rec.activate(("round-3", "meta:9")):
        with rec.span("dispatch") as d:
            pass
    assert rec.current() is None  # guard popped
    (s,) = rec.dump()
    assert s["trace_id"] == "round-3" and s["parent_id"] == "meta:9"
    assert d.span_id.startswith("worker1:")


def test_sampled_span_one_in_n_and_ctx_parenting():
    rec = SpanRecorder(role="serving1", sample_n=3)
    for _ in range(9):
        with rec.sampled_span("serving_read"):
            pass
    spans = rec.dump()
    assert len(spans) == 3
    assert all(s["trace_id"] == "sampled-serving1" for s in spans)
    # ctx= pulls the sampled read INTO the round's tree instead
    with rec.sampled_span("serving_read", ctx=("round-5", "meta:1")):
        pass
    tagged = rec.dump("round-5")
    assert len(tagged) == 1 and tagged[0]["parent_id"] == "meta:1"


def test_exception_inside_span_records_error_attr():
    rec = SpanRecorder(role="w", sample_n=1)
    with pytest.raises(ValueError):
        with rec.span("seal", trace_id="round-1"):
            raise ValueError("boom")
    (s,) = rec.dump()
    assert s["attrs"]["error"] == "ValueError"
    assert rec.current() is None  # TLS stack unwound despite the raise


def test_merge_dumps_dedups_and_orders():
    rec = SpanRecorder(role="w", sample_n=1)
    with rec.span("a", trace_id="round-1"):
        pass
    with rec.span("b", trace_id="round-1"):
        pass
    d = rec.dump()
    merged = merge_dumps([d, d, [d[1]]])  # pulled twice + partial
    assert [s["name"] for s in merged] == ["a", "b"]
    assert round_ids(merged) == [1]
    assert len(spans_for_round(merged, 1)) == 2


def test_truncated_dump_is_parseable_not_fatal():
    """The SIGKILL contract: a dead role's spans are simply absent.
    tree_check reports orphans/missing roots instead of raising."""
    meta = SpanRecorder(role="meta", sample_n=1)
    worker = SpanRecorder(role="worker1", sample_n=1)
    with meta.span("round", trace_id="round-2") as root:
        with worker.span("seal", ctx=root.ctx):
            pass
    # meta's dump lost (meta SIGKILLed): worker spans orphaned
    chk = tree_check(merge_dumps([worker.dump()]))
    assert not chk["complete"] and chk["orphans"]
    # worker's dump lost: meta-only tree still checks out
    chk2 = tree_check(merge_dumps([meta.dump()]))
    assert chk2["complete"] and chk2["roots"]


def test_chrome_export_is_loadable_trace_event_json():
    rec = SpanRecorder(role="meta", sample_n=1)
    with rec.span("round", trace_id="round-1", epoch=1):
        with rec.span("commit"):
            pass
    ct = json.loads(json.dumps(to_chrome_trace(rec.dump())))
    xs = [e for e in ct["traceEvents"] if e["ph"] == "X"]
    ms = [e for e in ct["traceEvents"] if e["ph"] == "M"]
    assert len(xs) == 2 and ms  # complete events + pid/tid metadata
    assert all(e["ts"] > 0 and e["dur"] >= 0 for e in xs)  # microsecs
    assert {e["name"] for e in xs} == {"round", "commit"}


# -- metrics plane -------------------------------------------------------
def test_render_prometheus_type_lines_and_le_convention():
    m = MetricsRegistry()
    m.inc("reqs", job="a")
    m.observe("lat_seconds", 0.003, job="a")
    m.observe("lat_seconds", 0.003, job="b")
    text = m.render_prometheus()
    # one # TYPE per family, not per labelset
    assert text.count("# TYPE lat_seconds histogram") == 1
    assert text.count("# TYPE reqs counter") == 1
    # le bounds render bare (0.005, not 5e-03 / 0.00500)
    assert 'le="0.005"' in text and 'le="+Inf"' in text
    assert "5e-" not in text


def test_merge_prometheus_injects_identity_labels():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.set_gauge("up", 1)
    a.inc("rows", job="q1")
    b.inc("rows", job="q1")
    merged = merge_prometheus([
        ({"role": "meta"}, a.render_prometheus()),
        ({"role": "worker1", "worker": "1"}, b.render_prometheus()),
    ])
    assert 'up{role="meta"} 1' in merged
    assert 'rows{job="q1",role="meta"} 1' in merged
    assert 'rows{job="q1",role="worker1",worker="1"} 1' in merged
    # TYPE lines dedup across scrapes and lead the output
    assert merged.count("# TYPE rows counter") == 1
    body = merged.split("\n")
    last_type = max(i for i, l in enumerate(body)
                    if l.startswith("# TYPE"))
    first_sample = min(i for i, l in enumerate(body)
                       if l and not l.startswith("#"))
    assert last_type < first_sample


def test_quantile_returns_bucket_upper_bound():
    m = MetricsRegistry()
    for v in (0.003, 0.003, 0.004, 0.2):
        m.observe("lat_seconds", v, job="a")
    from risingwave_tpu.common.metrics import _DEFAULT_BUCKETS

    # the answer is a bucket UPPER BOUND (conservative estimate): the
    # least boundary whose cumulative count reaches the quantile
    assert m.quantile("lat_seconds", 0.5, job="a") == 0.005
    assert m.quantile("lat_seconds", 1.0, job="a") == 0.25
    assert all(m.quantile("lat_seconds", q, job="a")
               in _DEFAULT_BUCKETS for q in (0.1, 0.5, 0.9))


# -- in-process cluster: propagation under faults ------------------------
def _cluster_cfg():
    from risingwave_tpu.common.config import RwConfig

    return RwConfig.from_dict({
        "streaming": {"chunk_size": 128},
        "state": {"agg_table_size": 512, "agg_emit_capacity": 128,
                  "mv_table_size": 512, "mv_ring_size": 1024},
        "storage": {"checkpoint_keep_epochs": 4},
    })


def _boot(tmp_path):
    from risingwave_tpu.cluster import ComputeWorker, MetaService

    meta = MetaService(str(tmp_path), heartbeat_timeout_s=60.0)
    meta.start(port=0, monitor=False, compactor=False)
    w = ComputeWorker(f"127.0.0.1:{meta.rpc_port}", str(tmp_path),
                      config=_cluster_cfg(),
                      heartbeat_interval_s=5.0).start()
    meta.execute_ddl(
        "CREATE SOURCE t (k BIGINT, v BIGINT) "
        "WITH (connector='datagen')"
    )
    meta.execute_ddl(
        "CREATE MATERIALIZED VIEW tm AS "
        "SELECT k % 4 AS g, count(*) AS n FROM t GROUP BY k % 4"
    )
    return meta, w


def test_retried_barrier_yields_exactly_one_span_tree(tmp_path):
    """FaultFabric eats two barrier RESPONSES: the meta's RetryPolicy
    re-sends, the worker answers from its round cache (re-running no
    chunks, recording no duplicate spans) — each round still assembles
    exactly ONE complete tree with one root and one seal."""
    from risingwave_tpu.common.faults import FaultFabric

    meta, w = _boot(tmp_path)
    try:
        assert meta.tick(1)["committed"]
        fab = faults_mod.install(FaultFabric())
        fab.fail_rpc(substr=">worker1/barrier",
                     mode="error_after_send", times=2)
        try:
            assert meta.tick(1)["committed"]
        finally:
            faults_mod.install(None)
        assert fab.injected.get("rpc", 0) >= 1

        tr = meta.cluster_trace(round=2)
        chk = tr["check"]
        assert chk["complete"], chk
        names = [s["name"] for s in tr["spans"]]
        assert names.count("round") == 1  # exactly one root
        assert names.count("seal") == 1  # chunks ran exactly once
        assert names.count("barrier") == 1  # one meta-side RPC span
        assert "commit" in names and "dispatch" in names
    finally:
        faults_mod.install(None)
        w.stop()
        meta.stop()


def test_failed_tick_reuses_round_root_no_duplicate_trees(tmp_path):
    """Multi-attempt dedup: a tick whose barrier is dropped outright
    leaves the round uncommitted; the NEXT tick for the same round
    attaches an ``attempt`` child to the CACHED root instead of
    opening a second root — one tree per round, by construction."""
    from risingwave_tpu.common.faults import FaultFabric

    meta, w = _boot(tmp_path)
    try:
        assert meta.tick(1)["committed"]
        # make barrier failure fast and terminal for ONE tick
        meta.retry.max_attempts = 1
        fab = faults_mod.install(FaultFabric())
        fab.fail_rpc(substr=">worker1/barrier", mode="drop", times=1)
        try:
            assert not meta.tick(1)["committed"]
        finally:
            faults_mod.install(None)
            meta.retry.max_attempts = 5
        res = meta.tick(1)
        assert res["committed"] and res["round"] == 2

        tr = meta.cluster_trace(round=2)
        chk = tr["check"]
        assert chk["complete"], chk
        names = [s["name"] for s in tr["spans"]]
        assert names.count("round") == 1
        assert "attempt" in names  # the retry rode the cached root
        assert names.count("seal") == 1
    finally:
        faults_mod.install(None)
        w.stop()
        meta.stop()


# -- DROP retires the scrape surface -------------------------------------
def test_drop_mv_and_index_retire_job_labeled_series():
    from risingwave_tpu.sql.engine import Engine

    eng = Engine(_cluster_cfg())
    eng.execute(
        "CREATE SOURCE t (k BIGINT, v BIGINT) "
        "WITH (connector='datagen')"
    )
    eng.execute(
        "CREATE MATERIALIZED VIEW m1 AS "
        "SELECT k % 4 AS g, count(*) AS n FROM t GROUP BY k % 4"
    )
    eng.execute("CREATE INDEX m1_g ON m1(g)")
    # enough barriers for the rolling spike-ratio gauge (min samples)
    eng.tick(barriers=10, chunks_per_barrier=1)
    text = eng.metrics.render_prometheus()
    assert 'barrier_phase_seconds_bucket{job="m1"' in text
    assert 'barrier_spike_ratio{job="m1"' in text

    eng.execute("DROP INDEX m1_g")
    text = eng.metrics.render_prometheus()
    assert 'job="m1_g"' not in text  # index series gone...
    assert 'barrier_phase_seconds_bucket{job="m1"' in text  # host stays

    eng.execute("DROP MATERIALIZED VIEW m1")
    text = eng.metrics.render_prometheus()
    assert 'job="m1"' not in text  # ...and the MV's whole footprint
