"""CH-benCHmark: concurrent OLTP + MV maintenance + serving, gated.

Boots the real 4-role cluster (in-process meta, N compute + 1 serving
subprocess), runs the seeded TPC-C transaction mix against the CH
analytical view group while serving reads concurrently, and asserts
the whole workload plane in one gate: ingest floor, barrier-commit
p99 ceiling, serving p99.9 ceiling, zero read errors, and every CH
view byte-identical to a single-node replay of the same seeded
transaction log.  Emits ``CH_BENCH.json``.

Run standalone (prints one JSON summary line)::

    python scripts/ch_bench.py --rounds 60 --assert

or the short ``slow``-marked pytest wrapper (tests/test_ch_bench.py,
which uses ``--small``).
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, ".")  # repo root


def main() -> None:
    from risingwave_tpu.workload.driver import (check, run,
                                                write_artifact)

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--rounds", type=int, default=60)
    p.add_argument("--seed", type=int, default=11)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--readers", type=int, default=2)
    p.add_argument("--chunks-per-barrier", type=int, default=1)
    p.add_argument("--small", action="store_true",
                   help="cheap-to-compile CH subset (CI wrapper)")
    p.add_argument("--min-ingest-rows-s", type=float, default=5.0,
                   help="sustained DML floor — sized for the 1-core "
                        "box where the ingest leader shares the core "
                        "with barrier maintenance")
    p.add_argument("--max-barrier-p99", type=float, default=120.0,
                   help="post-warmup barrier-commit p99 ceiling "
                        "(seconds) — generous for the 1-core box")
    p.add_argument("--max-serve-p999-ms", type=float, default=2000.0)
    p.add_argument("--assert", dest="check", action="store_true",
                   help="exit nonzero unless every SLO gate holds")
    args = p.parse_args()

    summary = run(rounds=args.rounds, seed=args.seed,
                  workers=args.workers, readers=args.readers,
                  small=args.small,
                  chunks_per_barrier=args.chunks_per_barrier)
    print(json.dumps(summary))
    write_artifact(summary)
    if args.check:
        bad = check(summary,
                    min_ingest_rows_s=args.min_ingest_rows_s,
                    max_barrier_p99_s=args.max_barrier_p99,
                    max_serve_p999_ms=args.max_serve_p999_ms)
        for b in bad:
            print(f"GATE: {b}", file=sys.stderr)
        raise SystemExit(1 if bad else 0)


if __name__ == "__main__":
    main()
