#!/bin/bash
# Probe the accelerator every ~20 min, forever; log to TPU_PROBE_LOG.jsonl
while true; do
  python "$(dirname "$0")/tpu_probe.py" 600 >/dev/null 2>&1
  sleep 1200
done
