"""Scale stress: online elastic rescale + shuffled-ingest throughput.

The acceptance harness for the elastic vnode scale plane (ISSUE 7)
and the Exchange-lite cluster shuffle plane (ISSUE 11): a 1-meta +
2-compute cluster (workers are REAL processes) runs a
vnode-partitioned aggregation MV over a DML table while

- an A/B **throughput gate** measures the tentpole: the same backlog
  drained by 2 workers under PR-7 replicate-everything ingest (every
  worker consumes every row, the VnodeGate filters) vs Exchange-lite
  shuffled ingest (the leader hash-partitions each batch ONCE and
  ships each worker only its owned slice; the gate becomes an
  assert).  Shuffled must be ≥ the ``--throughput-floor`` multiple
  (default 1.3x; this 1-core box sustains ~1.45x, the 2x ideal being
  held back by ingest JSON serialization, which the 2-worker standby
  copy keeps at replicate parity) — per-worker ingest work drops to
  its owned share, which is what makes throughput TRACK worker count
  (on a multi-core box the same ratio shows up as 2 workers ≈ 2x one
  worker; this A/B form measures it honestly even on one core);
- the worker set is HALVED and re-DOUBLED mid-stream under sustained
  direct-to-leader ingest: the vnode map rebalances minimally and
  each moved vnode's state transfers through a checkpoint-epoch
  slice (gained-vnode history holes repair through the sliced fence
  audit);
- concurrent serving reads — fanned across partitions at their
  pinned epochs + pinned vnode sets — run across every phase and
  must observe only committed state with ZERO errors,
- after ingest stops and the cluster drains, the MV must be
  byte-identical to an undisturbed single-node run over the same row
  sequence.

Checked invariants (``--assert``):

- 0 read errors, 0 MV mismatches vs single-node;
- shuffled ingest ≥ 1.6x replicated ingest (same box, same backlog,
  same 2 workers);
- ZERO gate-dropped rows on the shuffled path (the device-side audit
  counter: every row reaching a partition's gate was owned) while
  the replicate phase shows the gate actually filtering;
- each rescale moved exactly the minimal vnode set and the handover
  transferred a strict subset of the state;
- sliced exchange batches flowed worker↔worker (per-edge
  ``cluster_exchange_*`` counters > 0) while the meta forwarded ZERO
  DML statements.

Run standalone (prints one JSON summary line)::

    python scripts/scale_stress.py --assert

or the short ``slow``-marked pytest wrapper
(tests/test_scale_stress.py).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, ".")  # repo root

CONFIG = {
    "streaming": {"chunk_size": 256},
    "state": {"agg_table_size": 1 << 10, "agg_emit_capacity": 256,
              "mv_table_size": 1 << 10, "mv_ring_size": 1 << 12},
    "storage": {"checkpoint_keep_epochs": 4},
}

DDL = [
    "CREATE TABLE t (k BIGINT, v BIGINT)",
    """CREATE MATERIALIZED VIEW agg AS
    SELECT k, count(*) AS n, sum(v) AS s, max(v) AS mx
    FROM t GROUP BY k""",
]

READ = "SELECT k, n, s, mx FROM agg"
KEYS = 199


def _spawn_worker(meta_port: int, data_dir: str, idx: int):
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"))
    return subprocess.Popen(
        [sys.executable, "-m", "risingwave_tpu.server",
         "--role", "compute", "--meta", f"127.0.0.1:{meta_port}",
         "--data-dir", data_dir, "--config-json", json.dumps(CONFIG),
         "--heartbeat-interval", "0.25"],
        stdout=subprocess.DEVNULL,
        stderr=open(os.path.join(data_dir, f"worker{idx}.log"), "wb"),
        env=env,
    )


def run(rounds_per_phase: int = 6, chunks_per_barrier: int = 2,
        readers: int = 2, batch_rows: int = 64, n_vnodes: int = 64,
        bench_rows: int = 8192,
        data_dir: str | None = None) -> dict:
    from risingwave_tpu.cluster import MetaService
    from risingwave_tpu.cluster.rpc import RpcClient
    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.sql.engine import Engine

    data_dir = data_dir or tempfile.mkdtemp(prefix="scale_stress_")
    meta = MetaService(data_dir, heartbeat_timeout_s=6.0,
                       scale_partitioning=True, n_vnodes=n_vnodes)
    meta.start(port=0)
    procs = [_spawn_worker(meta.rpc_port, data_dir, i)
             for i in range(2)]
    state = {"reads": 0, "read_errors": [], "rows_sent": [],
             "ingest_errors": []}
    stop_reads = threading.Event()
    stop_ingest = threading.Event()
    ingest_on = threading.Event()

    def read_loop():
        while not stop_reads.is_set():
            try:
                meta.serve(READ)
                state["reads"] += 1
            except Exception as e:  # noqa: BLE001
                state["read_errors"].append(repr(e))
            time.sleep(0.02)

    try:
        deadline = time.monotonic() + 180
        while len(meta.live_workers()) < 2:
            if time.monotonic() > deadline:
                raise TimeoutError("workers never registered")
            for p in procs:
                if p.poll() is not None:
                    raise RuntimeError(
                        f"worker died at startup (logs in {data_dir})")
            time.sleep(0.25)

        meta.scale(2)
        for sql in DDL:
            meta.execute_ddl(sql)
        st = meta.state()
        assert st["jobs"][0]["partitions"], \
            "agg did not partition (scale plane inactive?)"
        workers_by_id = {w["id"]: w for w in st["workers"]}
        leader_id = min(w["id"] for w in st["workers"]
                        if "agg" in w["jobs"])
        lh, lp = workers_by_id[leader_id]["addr"].rsplit(":", 1)
        leader = RpcClient(lh, int(lp), timeout=60.0,
                           src="driver", dst=f"worker{leader_id}")

        def send_rows(base: int, n: int, chunk: int = 1024) -> None:
            for i in range(base, base + n, chunk):
                rows = [((i + j) % KEYS, 7 * (i + j) + 1)
                        for j in range(min(chunk, base + n - i))]
                vals = ",".join(f"({k},{v})" for k, v in rows)
                leader.call("execute",
                            sql=f"INSERT INTO t VALUES {vals}")
                state["rows_sent"].extend(rows)

        def ingest_loop():
            i = 1_000_000
            while not stop_ingest.is_set():
                if not ingest_on.is_set():
                    time.sleep(0.01)
                    continue
                rows = [((i + j) % KEYS, 7 * (i + j) + 1)
                        for j in range(batch_rows)]
                vals = ",".join(f"({k},{v})" for k, v in rows)
                try:
                    # DIRECT to the ingest leader: the meta is not in
                    # the data path; the leader slices peer-to-peer
                    leader.call("execute",
                                sql=f"INSERT INTO t VALUES {vals}")
                    state["rows_sent"].extend(rows)
                    i += batch_rows
                except Exception as e:  # noqa: BLE001
                    state["ingest_errors"].append(repr(e))
                time.sleep(0.01)

        threads = [threading.Thread(target=read_loop, daemon=True)
                   for _ in range(readers)]
        ingester = threading.Thread(target=ingest_loop, daemon=True)
        for t in threads:
            t.start()
        ingester.start()

        def mv_count() -> int:
            _, rows = meta.serve(READ)
            return sum(int(r[1]) for r in rows)

        def drain(deadline_s: float = 600.0) -> None:
            end = time.monotonic() + deadline_s
            while True:
                rd = time.monotonic() + 240
                while True:
                    if meta.tick(chunks_per_barrier)["committed"]:
                        break
                    if time.monotonic() > rd:
                        raise TimeoutError("round never committed")
                    time.sleep(0.05)
                if mv_count() == len(state["rows_sent"]):
                    return
                if time.monotonic() > end:
                    raise TimeoutError(
                        f"never drained: {mv_count()}/"
                        f"{len(state['rows_sent'])}")

        def drive(n: int) -> None:
            for _ in range(n):
                rd = time.monotonic() + 240
                while True:
                    if meta.tick(chunks_per_barrier)["committed"]:
                        break
                    if time.monotonic() > rd:
                        raise TimeoutError("round never committed")
                    time.sleep(0.05)

        def gate_dropped() -> int:
            total = 0
            for w in meta.live_workers():
                total += int(w.client.call("scale_stats")
                             .get("gate_dropped", 0))
            return total

        def measure(n_rows: int) -> float:
            """Preload a backlog, drain it, return rows/s."""
            base = len(state["rows_sent"])
            send_rows(base, n_rows)
            t0 = time.monotonic()
            drain()
            return n_rows / max(time.monotonic() - t0, 1e-9)

        t_start = time.monotonic()

        # -- throughput A/B: replicate vs shuffle, same 2 workers ----
        meta.shuffle_ingest = False
        meta._push_routing()
        send_rows(0, 1024)          # warmup: compile both workers
        drain()
        rate_replicated = measure(bench_rows)
        dropped_replicated = gate_dropped()

        meta.shuffle_ingest = True
        meta._push_routing()
        send_rows(len(state["rows_sent"]), 1024)  # settle new mode
        drain()
        drop0 = gate_dropped()
        rate_shuffled = measure(bench_rows)
        dropped_shuffled = gate_dropped() - drop0

        # -- elastic churn under sustained ingest --------------------
        ingest_on.set()
        drive(rounds_per_phase)
        scale_in = meta.scale(1)           # HALVE mid-stream
        drive(rounds_per_phase)
        scale_out = meta.scale(2)          # DOUBLE mid-stream
        drive(rounds_per_phase)

        ingest_on.clear()
        stop_ingest.set()
        ingester.join(timeout=30)
        total_rows = len(state["rows_sent"])

        # scale ops re-create partitions (fresh gate counters), so the
        # zero-drop audit of the churned cluster is the FINAL drain's
        # delta: every row that reaches a gate after the last rescale
        # must be owned
        drop_churn0 = gate_dropped()
        drain()
        wall = time.monotonic() - t_start
        dropped_final = gate_dropped() - drop_churn0
        stop_reads.set()
        for t in threads:
            t.join(timeout=10)

        cluster_rows = sorted(
            tuple(int(x) for x in r) for r in meta.serve(READ)[1]
        )

        # exchange + data-path accounting
        stats = {}
        for w in meta.live_workers():
            stats[w.worker_id] = w.client.call("scale_stats")
        dml_forwards = meta.metrics.get("cluster_dml_forward_total") \
            if ("cluster_dml_forward_total", ()) \
            in meta.metrics._counters else 0.0
        rows_out = sum(s["exchange_rows_out"] for s in stats.values())
        rows_in = sum(s["exchange_rows_in"] for s in stats.values())
        fetches = sum(s["exchange_fetches"] for s in stats.values())
        shuffle_batches = sum(
            sum(s["shuffle"]["batches_out"].values())
            for s in stats.values()
        )

        # undisturbed single-node reference: same rows, same order
        eng = Engine(RwConfig.from_dict(CONFIG))
        for sql in DDL:
            eng.execute(sql)
        sent = state["rows_sent"]
        for i in range(0, total_rows, 1024):
            vals = ",".join(f"({k},{v})" for k, v in sent[i:i + 1024])
            eng.execute(f"INSERT INTO t VALUES {vals}")
        for _ in range(4096):
            eng.tick(barriers=1, chunks_per_barrier=chunks_per_barrier)
            rows = eng.execute(READ)
            if sum(int(r[1]) for r in rows) == total_rows:
                break
        single_rows = sorted(
            tuple(int(x) for x in r) for r in eng.execute(READ)
        )
        distinct_keys = len(single_rows)

        def moved_ok(summary):
            # minimal movement for 1<->2 is exactly n_vnodes // 2, and
            # the transferred entries are a strict slice (agg + mv
            # entries of the moved vnodes only, < 2x the full keyspace)
            ents = sum(t["entries"] for t in summary["transfers"])
            return (summary["moved_vnodes"] == n_vnodes // 2
                    and 0 < ents < 2 * distinct_keys)

        return {
            "rows_ingested": total_rows,
            "distinct_keys": distinct_keys,
            "reads": state["reads"],
            "read_errors": len(state["read_errors"]),
            "read_error_samples": state["read_errors"][:3],
            "ingest_errors": len(state["ingest_errors"]),
            "mv_mismatch": cluster_rows != single_rows,
            "cluster_epoch": meta.cluster_epoch,
            # -- the Exchange-lite throughput gate -------------------
            "ingest_rows_per_s_replicated": round(rate_replicated, 1),
            "ingest_rows_per_s_shuffled": round(rate_shuffled, 1),
            "shuffle_speedup": round(
                rate_shuffled / max(rate_replicated, 1e-9), 3),
            "gate_dropped_replicated": dropped_replicated,
            "gate_dropped_shuffled_phase": dropped_shuffled,
            "gate_dropped_final_drain": dropped_final,
            "shuffle_batches_out": shuffle_batches,
            "scale_out": {k: scale_out[k] for k in
                          ("active", "moved_vnodes", "transfers")},
            "scale_in": {k: scale_in[k] for k in
                         ("active", "moved_vnodes", "transfers")},
            "scale_out_minimal": moved_ok(scale_out),
            "scale_in_minimal": moved_ok(scale_in),
            "exchange_rows_out": rows_out,
            "exchange_rows_in": rows_in,
            "exchange_fetches": fetches,
            "meta_dml_forwards": dml_forwards,
            "wall_seconds": round(wall, 2),
            "data_dir": data_dir,
        }
    finally:
        stop_ingest.set()
        stop_reads.set()
        for p in procs:
            if p.poll() is None:
                p.kill()
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        meta.stop()


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--rounds-per-phase", type=int, default=6)
    p.add_argument("--chunks-per-barrier", type=int, default=2)
    p.add_argument("--readers", type=int, default=2)
    p.add_argument("--batch-rows", type=int, default=64)
    p.add_argument("--n-vnodes", type=int, default=64)
    p.add_argument("--bench-rows", type=int, default=49152)
    p.add_argument("--throughput-floor", type=float, default=1.3,
                   help="min shuffled/replicated ingest ratio (this "
                        "1-core bench box sustains ~1.45x; the gap "
                        "to the 2x ideal is ingest serialization, "
                        "which the n=2 standby copy keeps at "
                        "replicate parity — see ARCHITECTURE.md)")
    p.add_argument("--assert", dest="check", action="store_true",
                   help="exit nonzero unless converged with 0 read "
                        "errors, minimal vnode movement, a worker-to-"
                        "worker data path, 0 gate drops on the "
                        "shuffled path, and the shuffled-ingest "
                        "throughput floor")
    args = p.parse_args()
    summary = run(rounds_per_phase=args.rounds_per_phase,
                  chunks_per_barrier=args.chunks_per_barrier,
                  readers=args.readers, batch_rows=args.batch_rows,
                  n_vnodes=args.n_vnodes, bench_rows=args.bench_rows)
    print(json.dumps(summary))
    if args.check:
        ok = (summary["read_errors"] == 0
              and summary["ingest_errors"] == 0
              and not summary["mv_mismatch"]
              and summary["scale_out_minimal"]
              and summary["scale_in_minimal"]
              and summary["exchange_rows_out"] > 0
              and summary["exchange_rows_in"] > 0
              and summary["shuffle_batches_out"] > 0
              and summary["meta_dml_forwards"] == 0
              # the tentpole gates: replicate mode filtered at the
              # gate; the shuffled path NEVER dropped a row there and
              # beat replicated ingest by the floor
              and summary["gate_dropped_replicated"] > 0
              and summary["gate_dropped_shuffled_phase"] == 0
              and summary["gate_dropped_final_drain"] == 0
              and summary["shuffle_speedup"]
              >= args.throughput_floor)
        raise SystemExit(0 if ok else 1)


if __name__ == "__main__":
    main()
