"""Chaos-lite campaign: seeded deterministic fault schedules against a
real multi-process cluster.

The acceptance harness for ISSUE 6 (the madsim-campaign analog): a
1-meta + 2-compute + 1-serving cluster (ALL four roles are real
processes) maintains two nexmark MVs through a seeded fault schedule
while concurrent serving reads run end-to-end.  Every schedule must
finish with

- ZERO read errors (reads retry through transient windows and must
  eventually answer from committed state only),
- ZERO stuck rounds (every requested global round commits),
- byte-identical final MV contents vs an undisturbed single-node run
  of the same config and round count.

Schedules (all deterministic: the fabric is counter-addressed and the
schedule expands from the seed via splitmix64 — same seed, same
faults, same replay):

- ``rpc_drop_storm``   drop + error-after-send storms on the meta's
                       control RPCs and the workers' meta-bound RPCs
                       (heartbeats included); retry/backoff and
                       round-tagged barriers must absorb everything;
- ``meta_kill``        SIGKILL the meta MID-ROUND, restart it on the
                       same RPC port over the same data_dir: it must
                       rebuild jobs + round position from the durable
                       MetaStore/manifest, workers and the serving
                       replica must re-register via backoff, the
                       interrupted round re-seals, and committing
                       resumes with no operator action;
- ``store_faults``     object-store put faults on the workers'
                       checkpoint uploads (lost AND durable-then-error
                       modes) during the pipelined async upload; the
                       uploader's RetryPolicy absorbs them off the
                       barrier path;
- ``scale_storm``      RPC drops on the worker↔worker EXCHANGE seam
                       (fan-out + catch-up fetch of a vnode-
                       partitioned job's replicated table) while the
                       cluster SCALES OUT mid-stream: retries plus
                       the barrier-fence repair fetch must absorb
                       every drop, the handover must move exactly the
                       minimal vnode set, and the MV must converge
                       byte-identically;
- ``corruption_storm`` seeded ``bit_flip``/``truncate`` payload
                       corruption on the workers' object-store puts
                       (MV-export SSTs AND checkpoint epoch uploads)
                       while rounds, serving reads, the compactor and
                       the meta scrubber all run: EVERY planted
                       corruption must be detected (typed
                       IntegrityError → durable quarantine note),
                       repaired (SST re-export from live job state /
                       checkpoint lineage rewind), with ZERO client-
                       visible read errors, zero silent wrong reads,
                       and byte-identical convergence;
- ``scale_kill``       SIGKILL the slice-transplant RECIPIENT between
                       the transplant and the donors' mask swap
                       during ``ctl cluster scale N`` (a seeded fabric
                       delay on the donor's repartition RPC holds the
                       window open): the transplanted state must
                       survive through the durably-sealed lineage,
                       the op must roll forward on retry, 0 read
                       errors, byte-identical convergence.

Run standalone (prints one JSON summary line per schedule)::

    python scripts/chaos_campaign.py --assert            # all three
    python scripts/chaos_campaign.py --schedule meta_kill --seed 11

or the short ``slow``-marked pytest wrapper
(tests/test_chaos_campaign.py).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, ".")  # repo root

CONFIG = {
    "streaming": {"chunk_size": 256},
    "state": {"agg_table_size": 1 << 10, "agg_emit_capacity": 256,
              "mv_table_size": 1 << 10, "mv_ring_size": 1 << 12},
    "storage": {"checkpoint_keep_epochs": 4},
}

DDL = [
    """CREATE SOURCE bid (
        auction BIGINT, bidder BIGINT, price BIGINT,
        channel VARCHAR, url VARCHAR, date_time TIMESTAMP
    ) WITH (connector = 'nexmark', nexmark.table = 'bid')""",
    """CREATE MATERIALIZED VIEW q7 AS
    SELECT window_start, max(price) AS max_price, count(*) AS bids
    FROM TUMBLE(bid, date_time, INTERVAL '1' SECOND)
    GROUP BY window_start""",
    """CREATE MATERIALIZED VIEW qcnt AS
    SELECT auction % 16 AS a, count(*) AS n, sum(price) AS vol
    FROM bid GROUP BY auction % 16""",
]

READS = [
    "SELECT window_start, max_price, bids FROM q7",
    "SELECT a, n, vol FROM qcnt",
]

SCHEDULES = ("rpc_drop_storm", "meta_kill", "store_faults",
             "scale_storm", "corruption_storm", "scale_kill",
             "shuffle_storm")

#: scale_storm topology: a vnode-partitioned aggregation over a
#: replicated DML table (the worker↔worker exchange seam under test)
SCALE_DDL = [
    "CREATE TABLE t (k BIGINT, v BIGINT)",
    """CREATE MATERIALIZED VIEW agg AS
    SELECT k, count(*) AS n, sum(v) AS s, max(v) AS mx
    FROM t GROUP BY k""",
]
SCALE_READ = "SELECT k, n, s, mx FROM agg"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_port(port: int, deadline_s: float = 120.0) -> None:
    """Block until something LISTENS on the port (a freshly spawned
    meta takes seconds to boot before peers can register)."""
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=1.0).close()
            return
        except OSError:
            if time.monotonic() > deadline:
                raise TimeoutError(f"port {port} never listened")
            time.sleep(0.2)


def _env(fault_env: dict | None) -> dict:
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"))
    env.pop("RWT_FAULTS", None)
    if fault_env:
        env["RWT_FAULTS"] = json.dumps(fault_env)
    return env


def _spawn_meta(data_dir: str, rpc_port: int, tag: str,
                fault_env: dict | None = None,
                scale_partitioning: bool = False,
                scrub_interval: float | None = None,
                serve_retry_timeout: float | None = None):
    argv = [sys.executable, "-m", "risingwave_tpu.server",
            "--role", "meta", "--port", str(_free_port()),
            "--rpc-port", str(rpc_port), "--data-dir", data_dir,
            "--heartbeat-timeout", "3.0",
            "--barrier-interval-ms", "0"]  # the driver owns the cadence
    if scale_partitioning:
        argv.append("--scale-partitioning")
    if scrub_interval is not None:
        argv += ["--scrub-interval", str(scrub_interval)]
    if serve_retry_timeout is not None:
        argv += ["--serve-retry-timeout", str(serve_retry_timeout)]
    proc = subprocess.Popen(
        argv,
        stdout=subprocess.DEVNULL,
        stderr=open(os.path.join(data_dir, f"meta_{tag}.log"), "wb"),
        env=_env(fault_env),
    )
    return proc


def _spawn_worker(rpc_port: int, data_dir: str, idx: int,
                  fault_env: dict | None = None):
    return subprocess.Popen(
        [sys.executable, "-m", "risingwave_tpu.server",
         "--role", "compute", "--meta", f"127.0.0.1:{rpc_port}",
         "--data-dir", data_dir, "--config-json", json.dumps(CONFIG),
         "--heartbeat-interval", "0.25"],
        stdout=subprocess.DEVNULL,
        stderr=open(os.path.join(data_dir, f"worker{idx}.log"), "wb"),
        env=_env(fault_env),
    )


def _spawn_serving(rpc_port: int, data_dir: str,
                   fault_env: dict | None = None):
    return subprocess.Popen(
        [sys.executable, "-m", "risingwave_tpu.server",
         "--role", "serving", "--meta", f"127.0.0.1:{rpc_port}",
         "--data-dir", data_dir, "--heartbeat-interval", "0.25"],
        stdout=subprocess.DEVNULL,
        stderr=open(os.path.join(data_dir, "serving.log"), "wb"),
        env=_env(fault_env),
    )


class MetaDriver:
    """Patient RPC driver: survives the meta being down/restarting
    (the client reconnects to whatever process owns the port)."""

    def __init__(self, rpc_port: int):
        from risingwave_tpu.cluster.rpc import RpcClient

        self.client = RpcClient("127.0.0.1", rpc_port, timeout=120.0,
                                src="driver", dst="meta")

    def call(self, method: str, deadline_s: float = 120.0, **params):
        from risingwave_tpu.cluster.rpc import RpcError

        deadline = time.monotonic() + deadline_s
        while True:
            try:
                return self.client.call(method, **params)
            except RpcError:
                raise  # the meta answered: final
            except (ConnectionError, OSError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)

    def close(self) -> None:
        self.client.close()


def _fault_envs(schedule: str, seed: int) -> dict:
    """Expand one (schedule, seed) into per-role ``RWT_FAULTS`` JSON.
    Pure function of the inputs — the determinism contract."""
    from risingwave_tpu.common.faults import FaultFabric

    if schedule == "rpc_drop_storm":
        meta_fab = FaultFabric.storm(
            seed, op="rpc", n=10, span=60,
            modes=("drop", "error_after_send"),
        )
        peer_fab = FaultFabric.storm(
            seed ^ 0x5A5A, op="rpc", substr=">meta/", n=5, span=80,
            modes=("drop",),
        )
        return {"meta": meta_fab.to_json(),
                "worker": peer_fab.to_json(),
                "serving": peer_fab.to_json()}
    if schedule == "store_faults":
        worker_fab = FaultFabric.storm(
            seed, op="put", substr="epoch_", n=6, span=50,
            modes=("before", "after"),
        )
        return {"worker": worker_fab.to_json()}
    if schedule == "scale_storm":
        # drops on the worker↔worker peer seam only: exchange fan-out,
        # catch-up fetch_table, repartition-era forwards — the labels
        # are ``worker{i}>worker{j}/<method>``, so ``>worker`` never
        # matches a worker's meta-bound RPCs
        peer_fab = FaultFabric.storm(
            seed, op="rpc", substr=">worker", n=8, span=8,
            modes=("drop",),
        )
        return {"worker": peer_fab.to_json()}
    if schedule == "corruption_storm":
        # payload corruption on the workers' shared-store uploads:
        # bit_flips on MV-export SSTs, bit_flip+truncate on checkpoint
        # epoch objects — every byte of both is crc-covered, so every
        # firing MUST surface as a typed IntegrityError somewhere
        # (serving read, compaction merge, or the scrub walk)
        fab = FaultFabric.storm(
            seed, op="put", substr="sst/", n=3, span=8,
            modes=("bit_flip",),
        )
        ck = FaultFabric.storm(
            seed ^ 0xC0FF, op="put", substr="/epoch_", n=2, span=20,
            modes=("bit_flip", "truncate"),
        )
        fab.rules += ck.rules
        return {"worker": fab.to_json()}
    if schedule == "shuffle_storm":
        # Exchange-lite seam under storm: seeded DROPS on the sliced
        # peer exchange plus ONE bounded one-way partition
        # (worker1>worker2 dark while worker2>worker1 flows) during
        # partitioned-JOIN ingest — lost sliced batches and the dark
        # direction must heal through the fence completeness audit
        # (fetch_slice / fetch_positions), never through the gate
        peer_fab = FaultFabric.storm(
            seed, op="rpc", substr=">worker", n=8, span=10,
            modes=("drop",),
        )
        peer_fab.partition("worker1", "worker2", times=4, after=20)
        return {"worker": peer_fab.to_json()}
    if schedule == "scale_kill":
        # ONE seeded delay on the donor's mask-swap RPC during the
        # handover (meta-side label ``meta>worker1/repartition``): the
        # recipient's transplant has landed, the donor's narrow is
        # held open — the deterministic window where the campaign
        # SIGKILLs the recipient
        fab = FaultFabric(seed=seed)
        fab.fail_rpc(substr=">worker1/repartition", after=0,
                     mode="delay", times=1, delay_s=3.0)
        return {"meta": fab.to_json()}
    return {}


def run_schedule(schedule: str, seed: int = 7, rounds: int = 10,
                 kill_at_round: int = 4, readers: int = 2,
                 data_dir: str | None = None) -> dict:
    assert schedule in SCHEDULES, schedule
    if schedule == "scale_storm":
        return run_scale_storm(seed=seed, rounds=rounds,
                               scale_at_round=kill_at_round,
                               readers=readers, data_dir=data_dir)
    if schedule == "scale_kill":
        return run_scale_kill(seed=seed, rounds=rounds,
                              scale_at_round=kill_at_round,
                              readers=readers, data_dir=data_dir)
    if schedule == "shuffle_storm":
        return run_shuffle_storm(seed=seed, rounds=rounds,
                                 scale_at_round=kill_at_round,
                                 readers=readers, data_dir=data_dir)
    data_dir = data_dir or tempfile.mkdtemp(
        prefix=f"chaos_{schedule}_")
    envs = _fault_envs(schedule, seed)
    # determinism spot-check: the same (schedule, seed) must expand to
    # the byte-identical fault schedule (no RNG anywhere in the path)
    deterministic = envs == _fault_envs(schedule, seed)

    storm = schedule == "corruption_storm"
    rpc_port = _free_port()
    meta_proc = _spawn_meta(
        data_dir, rpc_port, "a", fault_env=envs.get("meta"),
        # corruption_storm: fast background scrub cycles + patient
        # serving reads (repairs happen inside the read window)
        scrub_interval=2.0 if storm else None,
        serve_retry_timeout=180.0 if storm else None,
    )
    _wait_port(rpc_port)  # peers register against a LIVE meta
    procs = [_spawn_worker(rpc_port, data_dir, i,
                           fault_env=envs.get("worker"))
             for i in range(2)]
    serving_proc = _spawn_serving(rpc_port, data_dir,
                                  fault_env=envs.get("serving"))
    driver = MetaDriver(rpc_port)
    state = {"reads": 0, "read_errors": [], "tick_retries": 0,
             "meta_restarts": 0}
    stop = threading.Event()

    def read_loop():
        while not stop.is_set():
            for sql in READS:
                try:
                    driver.call("serve", sql=sql, deadline_s=180.0)
                    state["reads"] += 1
                except Exception as e:  # noqa: BLE001
                    state["read_errors"].append(repr(e))
            time.sleep(0.05)

    def drive_round(deadline_s: float = 240.0) -> None:
        deadline = time.monotonic() + deadline_s
        while True:
            try:
                res = driver.call("tick", chunks_per_barrier=1)
                if res["committed"]:
                    return
            except Exception:  # noqa: BLE001 — meta mid-restart
                pass
            state["tick_retries"] += 1
            if time.monotonic() > deadline:
                raise TimeoutError(f"round never committed "
                                   f"({schedule}, seed {seed})")
            time.sleep(0.2)

    try:
        deadline = time.monotonic() + 180
        while True:
            st = driver.call("cluster_state", deadline_s=120.0)
            if sum(w["alive"] for w in st["workers"]) >= 2 \
                    and st["serving"]:
                break
            for p in procs:
                if p.poll() is not None:
                    raise RuntimeError(
                        f"worker died at startup (logs in {data_dir})")
            if time.monotonic() > deadline:
                raise TimeoutError("cluster never assembled")
            time.sleep(0.25)

        for sql in DDL:
            driver.call("execute_ddl", sql=sql)

        threads = [threading.Thread(target=read_loop, daemon=True)
                   for _ in range(readers)]
        for t in threads:
            t.start()

        committed = 0
        while committed < rounds:
            drive_round()
            committed = int(driver.call(
                "cluster_state")["cluster_epoch"])
            if storm:
                # scrub EVERY round: a corrupt checkpoint epoch must
                # be caught before retention GC rotates it out —
                # detection + (synchronous) repair per cycle
                driver.call("cluster_scrub", deadline_s=300.0)
            if schedule == "meta_kill" and committed == kill_at_round \
                    and state["meta_restarts"] == 0:
                # SIGKILL MID-ROUND: launch the next round, give the
                # barriers a moment to be in flight, then kill
                t = threading.Thread(
                    target=lambda: _swallow(
                        lambda: driver.call("tick",
                                            chunks_per_barrier=1)),
                    daemon=True)
                t.start()
                time.sleep(0.3)
                meta_proc.send_signal(signal.SIGKILL)
                meta_proc.wait(timeout=10)
                t.join(timeout=30)
                meta_proc = _spawn_meta(data_dir, rpc_port, "b",
                                        fault_env=envs.get("meta"))
                state["meta_restarts"] += 1

        stop.set()
        for t in threads:
            t.join(timeout=15)

        final_scrub = None
        if storm:
            # drain: keep scrubbing until nothing corrupt remains in
            # reach (repairs are synchronous within each cycle)
            for _ in range(6):
                final_scrub = driver.call("cluster_scrub",
                                          deadline_s=300.0)
                if not final_scrub["corrupt"]:
                    break
                time.sleep(0.5)
        final_state = driver.call("cluster_state")
        faults = driver.call("cluster_faults")
        cluster_rows = [
            sorted(tuple(v) for v in driver.call(
                "serve", sql=sql)["rows"])
            for sql in READS
        ]
    finally:
        stop.set()
        for p in procs + [serving_proc, meta_proc]:
            if p.poll() is None:
                p.kill()
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        driver.close()

    # undisturbed single-node reference (same config + rounds)
    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.sql.engine import Engine

    eng = Engine(RwConfig.from_dict(CONFIG))
    for sql in DDL:
        eng.execute(sql)
    eng.tick(barriers=rounds, chunks_per_barrier=1)
    single_rows = [
        sorted(tuple(int(x) for x in r) for r in eng.execute(sql))
        for sql in READS
    ]
    mismatches = sum(c != s for c, s in zip(cluster_rows, single_rows))

    worker_faults = [v for v in faults["workers"].values() if v]
    injected = sum((v["fabric"] or {}).get("injected_total", 0)
                   for v in worker_faults + [faults["meta"]]
                   + [v for v in faults["serving"].values() if v])
    peer_retries = sum(v["rpc_retries_total"] for v in worker_faults)
    upload_retries = sum(v.get("checkpoint_upload_retries_total", 0)
                         for v in worker_faults)
    planted = sorted({
        k for v in worker_faults
        for k in (v["fabric"] or {}).get("corrupted_keys", [])
    })
    detected = sorted(set((final_scrub or {}).get("quarantined", [])))
    summary = {
        "schedule": schedule,
        "seed": seed,
        "deterministic_expansion": deterministic,
        "rounds": rounds,
        "rounds_committed": int(final_state["cluster_epoch"]),
        "meta_recovered": bool(final_state.get("recovered")),
        "meta_restarts": state["meta_restarts"],
        "live_workers": sum(w["alive"]
                            for w in final_state["workers"]),
        "serving_replicas": len(final_state["serving"]),
        "worker_registrations": sum(
            v.get("registrations", 0) for v in worker_faults),
        "reads": state["reads"],
        "read_errors": len(state["read_errors"]),
        "read_error_samples": state["read_errors"][:3],
        "tick_retries": state["tick_retries"],
        "faults_injected": injected,
        "meta_rpc_retries": faults["meta"]["rpc_retries_total"],
        "peer_rpc_retries": peer_retries,
        "upload_retries": upload_retries,
        "corruptions_planted": planted,
        "corruptions_detected": detected,
        "all_corruptions_detected":
            bool(planted) and set(planted) <= set(detected),
        "repairs": (final_scrub or {}).get("repairs", {}),
        "scrub_unrepaired":
            len((final_scrub or {}).get("corrupt", [])),
        "mv_mismatches": mismatches,
        "mv_rows": [len(r) for r in cluster_rows],
        "data_dir": data_dir,
    }
    summary["ok"] = bool(
        summary["deterministic_expansion"]
        and summary["read_errors"] == 0
        and summary["rounds_committed"] >= rounds
        and summary["mv_mismatches"] == 0
        and summary["live_workers"] == 2
        and _schedule_ok(schedule, summary)
    )
    return summary


def _schedule_ok(schedule: str, s: dict) -> bool:
    if schedule == "rpc_drop_storm":
        # the storm actually fired and the retry budget absorbed it
        return s["faults_injected"] > 0 \
            and (s["meta_rpc_retries"] + s["peer_rpc_retries"]
                 + s["tick_retries"]) > 0
    if schedule == "meta_kill":
        # the restarted meta REBUILT its state from the durable logs
        # and every peer re-registered without operator action
        return s["meta_restarts"] == 1 and s["meta_recovered"] \
            and s["worker_registrations"] >= 4 \
            and s["serving_replicas"] >= 1
    if schedule == "store_faults":
        # faults hit the async upload path and were retried there
        return s["faults_injected"] > 0 and s["upload_retries"] > 0
    if schedule == "corruption_storm":
        # every planted corruption detected (quarantine note per
        # corrupted object), every reachable one repaired, and at
        # least one repair of each class actually ran
        return s["all_corruptions_detected"] \
            and s["scrub_unrepaired"] == 0 \
            and sum(s["repairs"].values()) > 0
    return True


def run_scale_storm(seed: int = 7, rounds: int = 10,
                    scale_at_round: int = 4, readers: int = 2,
                    data_dir: str | None = None) -> dict:
    """Seeded drops on the worker↔worker exchange seam while the
    cluster scales out mid-stream (see module docstring)."""
    data_dir = data_dir or tempfile.mkdtemp(prefix="chaos_scale_")
    envs = _fault_envs("scale_storm", seed)
    deterministic = envs == _fault_envs("scale_storm", seed)

    rpc_port = _free_port()
    meta_proc = _spawn_meta(data_dir, rpc_port, "a",
                            scale_partitioning=True)
    _wait_port(rpc_port)
    procs = [_spawn_worker(rpc_port, data_dir, i,
                           fault_env=envs.get("worker"))
             for i in range(2)]
    driver = MetaDriver(rpc_port)
    state = {"reads": 0, "read_errors": [], "tick_retries": 0,
             "rows": []}
    stop = threading.Event()

    def read_loop():
        while not stop.is_set():
            try:
                driver.call("serve", sql=SCALE_READ, deadline_s=180.0)
                state["reads"] += 1
            except Exception as e:  # noqa: BLE001
                state["read_errors"].append(repr(e))
            time.sleep(0.05)

    def drive_round(deadline_s: float = 240.0) -> None:
        deadline = time.monotonic() + deadline_s
        while True:
            res = driver.call("tick", chunks_per_barrier=2)
            if res["committed"]:
                return
            state["tick_retries"] += 1
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"round never committed (scale_storm, seed {seed})")
            time.sleep(0.2)

    try:
        deadline = time.monotonic() + 180
        while True:
            st = driver.call("cluster_state", deadline_s=120.0)
            if sum(w["alive"] for w in st["workers"]) >= 2:
                break
            for p in procs:
                if p.poll() is not None:
                    raise RuntimeError(
                        f"worker died at startup (logs in {data_dir})")
            if time.monotonic() > deadline:
                raise TimeoutError("cluster never assembled")
            time.sleep(0.25)

        driver.call("cluster_scale", n=1)  # capacity starts at ONE
        for sql in SCALE_DDL:
            driver.call("execute_ddl", sql=sql)

        def ingest(i0: int, n: int) -> None:
            rows = [((i0 + j) % 97, 3 * (i0 + j) + 1) for j in range(n)]
            vals = ",".join(f"({k},{v})" for k, v in rows)
            # the meta forwards ONE statement to the ingest leader;
            # the leader's fan-out (the seam under storm) is peer RPC
            driver.call("execute_ddl",
                        sql=f"INSERT INTO t VALUES {vals}")
            state["rows"].extend(rows)

        threads = [threading.Thread(target=read_loop, daemon=True)
                   for _ in range(readers)]
        for t in threads:
            t.start()

        scale_out = None
        i0 = 0
        committed = 0
        while committed < rounds:
            # several small batches per round: each fan-out is one
            # peer RPC, so the storm has real traffic to hit
            for _ in range(4):
                ingest(i0, 24)
                i0 += 24
            drive_round()
            committed = int(driver.call(
                "cluster_state")["cluster_epoch"])
            if scale_out is None and committed >= scale_at_round:
                # DOUBLE mid-stream, exchange storm active
                scale_out = driver.call("cluster_scale", n=2,
                                        deadline_s=600.0)
        total = len(state["rows"])
        drain_deadline = time.monotonic() + 300
        while True:
            drive_round()
            rows = driver.call("serve", sql=SCALE_READ)["rows"]
            if sum(int(r[1]) for r in rows) == total:
                break
            if time.monotonic() > drain_deadline:
                raise TimeoutError("scale_storm never drained")

        stop.set()
        for t in threads:
            t.join(timeout=15)
        faults = driver.call("cluster_faults")
        final_state = driver.call("cluster_state")
        cluster_rows = sorted(
            tuple(int(x) for x in r)
            for r in driver.call("serve", sql=SCALE_READ)["rows"]
        )
    finally:
        stop.set()
        for p in procs + [meta_proc]:
            if p.poll() is None:
                p.kill()
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        driver.close()

    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.sql.engine import Engine

    eng = Engine(RwConfig.from_dict(CONFIG))
    for sql in SCALE_DDL:
        eng.execute(sql)
    sent = state["rows"]
    for i in range(0, len(sent), 1024):
        vals = ",".join(f"({k},{v})" for k, v in sent[i:i + 1024])
        eng.execute(f"INSERT INTO t VALUES {vals}")
    for _ in range(4096):
        eng.tick(barriers=1, chunks_per_barrier=2)
        if sum(int(r[1]) for r in eng.execute(SCALE_READ)) \
                == len(sent):
            break
    single_rows = sorted(
        tuple(int(x) for x in r) for r in eng.execute(SCALE_READ)
    )

    worker_faults = [v for v in faults["workers"].values() if v]
    injected = sum((v["fabric"] or {}).get("injected_total", 0)
                   for v in worker_faults)
    absorbed = sum(v["rpc_retries_total"]
                   + v.get("exchange_fetches", 0)
                   + v.get("exchange_send_failures", 0)
                   for v in worker_faults)
    summary = {
        "schedule": "scale_storm",
        "seed": seed,
        "deterministic_expansion": deterministic,
        "rounds": rounds,
        "rounds_committed": int(final_state["cluster_epoch"]),
        "rows_ingested": len(sent),
        "reads": state["reads"],
        "read_errors": len(state["read_errors"]),
        "read_error_samples": state["read_errors"][:3],
        "tick_retries": state["tick_retries"],
        "scale_out_moved_vnodes":
            scale_out["moved_vnodes"] if scale_out else 0,
        "active_workers":
            final_state["scale"]["active_workers"],
        "faults_injected": injected,
        "exchange_faults_absorbed": absorbed,
        "exchange_rows_in": sum(v.get("exchange_rows_in", 0)
                                for v in worker_faults),
        "mv_mismatches": int(cluster_rows != single_rows),
        "mv_rows": len(cluster_rows),
        "data_dir": data_dir,
    }
    summary["ok"] = bool(
        summary["deterministic_expansion"]
        and summary["read_errors"] == 0
        and summary["rounds_committed"] >= rounds
        and summary["mv_mismatches"] == 0
        and summary["scale_out_moved_vnodes"] == 32
        and summary["faults_injected"] > 0
        and summary["exchange_faults_absorbed"] > 0
        and summary["active_workers"] == [1, 2]
    )
    return summary


#: shuffle_storm topology: a vnode-PARTITIONED JOIN over two sliced-
#: ingest tables — the Exchange-lite seam under storm.  LEFT OUTER so
#: mid-stream b-arrivals retract their pad rows (retraction churn
#: through the chaos window).
SHUFFLE_DDL = [
    "CREATE TABLE a (k BIGINT, v BIGINT)",
    "CREATE TABLE b (k BIGINT, w BIGINT)",
    """CREATE MATERIALIZED VIEW j AS
    SELECT a.k AS k, a.v AS v, b.w AS w
    FROM a LEFT JOIN b ON a.k = b.k""",
]
SHUFFLE_READ = "SELECT k, v, w FROM j"
SHUFFLE_KEYS = 97


def run_shuffle_storm(seed: int = 7, rounds: int = 10,
                      scale_at_round: int = 4, readers: int = 2,
                      data_dir: str | None = None) -> dict:
    """Seeded drops + a one-way partition on the SLICED exchange seam
    during partitioned-JOIN ingest (see module docstring): lost
    sliced batches heal through the fence completeness audit, reads
    stay zero-error, the join MV converges byte-identical, and the
    gate audit counters prove no row ever reached a partition it did
    not own."""
    data_dir = data_dir or tempfile.mkdtemp(prefix="chaos_shuffle_")
    envs = _fault_envs("shuffle_storm", seed)
    deterministic = envs == _fault_envs("shuffle_storm", seed)

    rpc_port = _free_port()
    meta_proc = _spawn_meta(data_dir, rpc_port, "a",
                            scale_partitioning=True)
    _wait_port(rpc_port)
    procs = [_spawn_worker(rpc_port, data_dir, i,
                           fault_env=envs.get("worker"))
             for i in range(2)]
    driver = MetaDriver(rpc_port)
    state = {"reads": 0, "read_errors": [], "tick_retries": 0,
             "rows_a": [], "rows_b": []}
    stop = threading.Event()

    def read_loop():
        while not stop.is_set():
            try:
                driver.call("serve", sql=SHUFFLE_READ,
                            deadline_s=180.0)
                state["reads"] += 1
            except Exception as e:  # noqa: BLE001
                state["read_errors"].append(repr(e))
            time.sleep(0.05)

    def drive_round(deadline_s: float = 240.0) -> None:
        deadline = time.monotonic() + deadline_s
        while True:
            res = driver.call("tick", chunks_per_barrier=2)
            if res["committed"]:
                return
            state["tick_retries"] += 1
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"round never committed (shuffle_storm, "
                    f"seed {seed})")
            time.sleep(0.2)

    try:
        deadline = time.monotonic() + 180
        while True:
            st = driver.call("cluster_state", deadline_s=120.0)
            if sum(w["alive"] for w in st["workers"]) >= 2:
                break
            for p in procs:
                if p.poll() is not None:
                    raise RuntimeError(
                        f"worker died at startup (logs in {data_dir})")
            if time.monotonic() > deadline:
                raise TimeoutError("cluster never assembled")
            time.sleep(0.25)

        driver.call("cluster_scale", n=2)  # partitioned from round 0
        for sql in SHUFFLE_DDL:
            driver.call("execute_ddl", sql=sql)

        def ingest_a(i0: int, n: int) -> None:
            rows = [((i0 + j) % SHUFFLE_KEYS, 3 * (i0 + j) + 1)
                    for j in range(n)]
            vals = ",".join(f"({k},{v})" for k, v in rows)
            driver.call("execute_ddl",
                        sql=f"INSERT INTO a VALUES {vals}")
            state["rows_a"].extend(rows)

        def ingest_b(ks) -> None:
            rows = [(k, 1000 + 7 * k) for k in ks]
            vals = ",".join(f"({k},{w})" for k, w in rows)
            driver.call("execute_ddl",
                        sql=f"INSERT INTO b VALUES {vals}")
            state["rows_b"].extend(rows)

        # half the keys matched up front; the other half arrives
        # MID-storm so every pad row retracts under fire
        ingest_b(range(0, SHUFFLE_KEYS, 2))

        threads = [threading.Thread(target=read_loop, daemon=True)
                   for _ in range(readers)]
        for t in threads:
            t.start()

        i0 = 0
        committed = 0
        b_late = False
        while committed < rounds:
            for _ in range(4):
                ingest_a(i0, 24)
                i0 += 24
            drive_round()
            committed = int(driver.call(
                "cluster_state")["cluster_epoch"])
            if not b_late and committed >= scale_at_round:
                b_late = True
                ingest_b(range(1, SHUFFLE_KEYS, 2))
        total_a = len(state["rows_a"])
        # left outer with exactly one b-row per key: |j| == |a|
        drain_deadline = time.monotonic() + 300
        while True:
            drive_round()
            rows = driver.call("serve", sql=SHUFFLE_READ)["rows"]
            if len(rows) == total_a \
                    and all(r[2] is not None for r in rows):
                break
            if time.monotonic() > drain_deadline:
                raise TimeoutError("shuffle_storm never drained")

        stop.set()
        for t in threads:
            t.join(timeout=15)
        faults = driver.call("cluster_faults")
        final_state = driver.call("cluster_state")
        cluster_rows = sorted(
            tuple(int(x) for x in r)
            for r in driver.call("serve", sql=SHUFFLE_READ)["rows"]
        )
    finally:
        stop.set()
        for p in procs + [meta_proc]:
            if p.poll() is None:
                p.kill()
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        driver.close()

    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.sql.engine import Engine

    eng = Engine(RwConfig.from_dict(CONFIG))
    for sql in SHUFFLE_DDL:
        eng.execute(sql)
    b1 = state["rows_b"][:len(range(0, SHUFFLE_KEYS, 2))]
    b2 = state["rows_b"][len(b1):]
    eng.execute("INSERT INTO b VALUES "
                + ",".join(f"({k},{w})" for k, w in b1))
    sent = state["rows_a"]
    # replay a in the same interleaving: first-half b, then a up to
    # the late-b position, then late b, then the rest — the join is
    # retraction-consistent so only the FINAL state must match, and
    # it does for any interleaving once all rows land
    for i in range(0, len(sent), 1024):
        vals = ",".join(f"({k},{v})" for k, v in sent[i:i + 1024])
        eng.execute(f"INSERT INTO a VALUES {vals}")
    if b2:
        eng.execute("INSERT INTO b VALUES "
                    + ",".join(f"({k},{w})" for k, w in b2))
    for _ in range(4096):
        eng.tick(barriers=1, chunks_per_barrier=2)
        rows = eng.execute(SHUFFLE_READ)
        if len(rows) == len(sent) \
                and all(r[2] is not None for r in rows):
            break
    single_rows = sorted(
        tuple(int(x) for x in r) for r in eng.execute(SHUFFLE_READ)
    )

    worker_faults = [v for v in faults["workers"].values() if v]
    injected = sum((v["fabric"] or {}).get("injected_total", 0)
                   for v in worker_faults)
    absorbed = sum(v["rpc_retries_total"]
                   + v.get("exchange_fetches", 0)
                   + v.get("exchange_send_failures", 0)
                   for v in worker_faults)
    summary = {
        "schedule": "shuffle_storm",
        "seed": seed,
        "deterministic_expansion": deterministic,
        "rounds": rounds,
        "rounds_committed": int(final_state["cluster_epoch"]),
        "rows_ingested": len(sent),
        "reads": state["reads"],
        "read_errors": len(state["read_errors"]),
        "read_error_samples": state["read_errors"][:3],
        "tick_retries": state["tick_retries"],
        "faults_injected": injected,
        "exchange_faults_absorbed": absorbed,
        "shuffled_tables": list((final_state.get("exchange") or {})
                                .get("tables", {})),
        "mv_mismatches": int(cluster_rows != single_rows),
        "mv_rows": len(cluster_rows),
        "partitions": len(final_state["jobs"][0]["partitions"] or []),
        "data_dir": data_dir,
    }
    summary["ok"] = bool(
        summary["deterministic_expansion"]
        and summary["read_errors"] == 0
        and summary["rounds_committed"] >= rounds
        and summary["mv_mismatches"] == 0
        and summary["partitions"] == 2
        and summary["faults_injected"] > 0
        and summary["exchange_faults_absorbed"] > 0
        and sorted(summary["shuffled_tables"]) == ["a", "b"]
    )
    return summary


def run_scale_kill(seed: int = 7, rounds: int = 8,
                   scale_at_round: int = 3, readers: int = 2,
                   data_dir: str | None = None) -> dict:
    """SIGKILL the slice-transplant recipient mid-``cluster scale``
    (see module docstring): the seeded fabric delays the DONOR's
    mask-swap RPC, holding open the window between the recipient's
    transplant and the donors' narrow; the campaign kills the
    recipient inside it.  The transplanted state must survive through
    the durably-sealed lineage (failover re-adopts it on the spare
    worker), the interrupted scale op must roll forward on retry, and
    the MV must converge byte-identically with 0 read errors."""
    data_dir = data_dir or tempfile.mkdtemp(prefix="chaos_scalekill_")
    envs = _fault_envs("scale_kill", seed)
    deterministic = envs == _fault_envs("scale_kill", seed)

    rpc_port = _free_port()
    meta_proc = _spawn_meta(data_dir, rpc_port, "a",
                            fault_env=envs.get("meta"),
                            scale_partitioning=True,
                            serve_retry_timeout=300.0)
    _wait_port(rpc_port)
    driver = MetaDriver(rpc_port)
    scaler = MetaDriver(rpc_port)  # scale blocks for minutes: own conn
    procs = []
    state = {"reads": 0, "read_errors": [], "tick_retries": 0,
             "rows": []}
    stop = threading.Event()

    def read_loop():
        while not stop.is_set():
            try:
                driver.call("serve", sql=SCALE_READ, deadline_s=420.0)
                state["reads"] += 1
            except Exception as e:  # noqa: BLE001
                state["read_errors"].append(repr(e))
            time.sleep(0.05)

    def drive_round(deadline_s: float = 420.0) -> None:
        deadline = time.monotonic() + deadline_s
        while True:
            try:
                res = driver.call("tick", chunks_per_barrier=2)
                if res["committed"]:
                    return
            except Exception:  # noqa: BLE001 — stalled scale window
                pass
            state["tick_retries"] += 1
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"round never committed (scale_kill, seed {seed})")
            time.sleep(0.2)

    def ingest(i0: int, n: int) -> None:
        rows = [((i0 + j) % 83, 5 * (i0 + j) + 2) for j in range(n)]
        vals = ",".join(f"({k},{v})" for k, v in rows)
        driver.call("execute_ddl", sql=f"INSERT INTO t VALUES {vals}")
        state["rows"].extend(rows)

    scale_res: dict = {}
    try:
        # spawn workers ONE AT A TIME: registration order fixes the
        # worker ids the seeded schedule addresses (worker1 = donor)
        deadline = time.monotonic() + 240
        for i in range(3):
            procs.append(_spawn_worker(rpc_port, data_dir, i))
            while True:
                st = driver.call("cluster_state", deadline_s=120.0)
                if sum(w["alive"] for w in st["workers"]) >= i + 1:
                    break
                if procs[i].poll() is not None:
                    raise RuntimeError(
                        f"worker died at startup (logs in {data_dir})")
                if time.monotonic() > deadline:
                    raise TimeoutError("cluster never assembled")
                time.sleep(0.25)

        driver.call("cluster_scale", n=1)  # donor owns everything
        for sql in SCALE_DDL:
            driver.call("execute_ddl", sql=sql)

        threads = [threading.Thread(target=read_loop, daemon=True)
                   for _ in range(readers)]
        for t in threads:
            t.start()

        i0 = 0
        committed = 0
        while committed < scale_at_round:
            for _ in range(3):
                ingest(i0, 24)
                i0 += 24
            drive_round()
            committed = int(driver.call(
                "cluster_state")["cluster_epoch"])

        # scale 1 -> 2 in a thread; the donor's narrow is delayed by
        # the fabric, so the recipient's transplant is observable
        # BEFORE the mask swap — the kill window
        def do_scale():
            try:
                scale_res["first"] = scaler.call(
                    "cluster_scale", n=2, deadline_s=600.0)
            except Exception as e:  # noqa: BLE001 — expected: stall
                scale_res["first_error"] = repr(e)

        t_scale = threading.Thread(target=do_scale, daemon=True)
        t_scale.start()
        kill_deadline = time.monotonic() + 120
        while True:
            st = driver.call("cluster_state", deadline_s=120.0)
            job = next((j for j in st["jobs"] if j["name"] == "agg"),
                       None)
            parts = (job or {}).get("partitions") or []
            if any(p["worker"] == 2 and p["vnodes"] > 0
                   for p in parts):
                break  # transplant landed on the recipient
            if time.monotonic() > kill_deadline:
                raise TimeoutError("transplant to recipient never "
                                   "became visible")
            time.sleep(0.05)
        procs[1].send_signal(signal.SIGKILL)  # the recipient dies
        procs[1].wait(timeout=10)
        t_scale.join(timeout=600)

        # failover: the dead recipient's lineage (WITH the durably
        # sealed transplanted slice) re-adopts on the spare worker
        drive_round(deadline_s=420.0)
        # the interrupted op rolls forward on retry
        scale_res["retry"] = scaler.call("cluster_scale", n=2,
                                         deadline_s=600.0)

        while committed < rounds:
            for _ in range(3):
                ingest(i0, 24)
                i0 += 24
            drive_round()
            committed = int(driver.call(
                "cluster_state")["cluster_epoch"])
        total = len(state["rows"])
        drain_deadline = time.monotonic() + 420
        while True:
            drive_round()
            rows = driver.call("serve", sql=SCALE_READ)["rows"]
            if sum(int(r[1]) for r in rows) == total:
                break
            if time.monotonic() > drain_deadline:
                raise TimeoutError("scale_kill never drained")

        stop.set()
        for t in threads:
            t.join(timeout=15)
        final_state = driver.call("cluster_state")
        cluster_rows = sorted(
            tuple(int(x) for x in r)
            for r in driver.call("serve", sql=SCALE_READ)["rows"]
        )
    finally:
        stop.set()
        for p in procs + [meta_proc]:
            if p.poll() is None:
                p.kill()
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        driver.close()
        scaler.close()

    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.sql.engine import Engine

    eng = Engine(RwConfig.from_dict(CONFIG))
    for sql in SCALE_DDL:
        eng.execute(sql)
    sent = state["rows"]
    for i in range(0, len(sent), 1024):
        vals = ",".join(f"({k},{v})" for k, v in sent[i:i + 1024])
        eng.execute(f"INSERT INTO t VALUES {vals}")
    for _ in range(4096):
        eng.tick(barriers=1, chunks_per_barrier=2)
        if sum(int(r[1]) for r in eng.execute(SCALE_READ)) \
                == len(sent):
            break
    single_rows = sorted(
        tuple(int(x) for x in r) for r in eng.execute(SCALE_READ)
    )

    summary = {
        "schedule": "scale_kill",
        "seed": seed,
        "deterministic_expansion": deterministic,
        "rounds": rounds,
        "rounds_committed": int(final_state["cluster_epoch"]),
        "rows_ingested": len(sent),
        "reads": state["reads"],
        "read_errors": len(state["read_errors"]),
        "read_error_samples": state["read_errors"][:3],
        "tick_retries": state["tick_retries"],
        "first_scale_error": scale_res.get("first_error"),
        "first_scale": scale_res.get("first"),
        "retry_scale_ok": "retry" in scale_res,
        "active_workers": final_state["scale"]["active_workers"],
        "mv_mismatches": int(cluster_rows != single_rows),
        "mv_rows": len(cluster_rows),
        "data_dir": data_dir,
    }
    summary["ok"] = bool(
        summary["deterministic_expansion"]
        and summary["read_errors"] == 0
        and summary["rounds_committed"] >= rounds
        and summary["mv_mismatches"] == 0
        and summary["retry_scale_ok"]
        # the kill interrupted the first op OR the op absorbed the
        # death entirely — either way the retry rolled it forward
        and (summary["first_scale_error"] is not None
             or summary["first_scale"] is not None)
    )
    return summary


def _swallow(fn) -> None:
    try:
        fn()
    except Exception:  # noqa: BLE001 — the kill window eats the call
        pass


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--schedule", choices=SCHEDULES + ("all",),
                   default="all")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--rounds", type=int, default=10)
    p.add_argument("--kill-at-round", type=int, default=4)
    p.add_argument("--readers", type=int, default=2)
    p.add_argument("--assert", dest="check", action="store_true",
                   help="exit nonzero unless every schedule converged "
                        "with 0 read errors and 0 stuck rounds")
    args = p.parse_args()

    names = SCHEDULES if args.schedule == "all" else (args.schedule,)
    ok = True
    for name in names:
        summary = run_schedule(
            name, seed=args.seed, rounds=args.rounds,
            kill_at_round=args.kill_at_round, readers=args.readers,
        )
        print(json.dumps(summary), flush=True)
        ok = ok and summary["ok"]
    if args.check:
        raise SystemExit(0 if ok else 1)


if __name__ == "__main__":
    main()
