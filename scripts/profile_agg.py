"""Microprofile hash-agg internals on the current backend."""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import risingwave_tpu  # noqa: F401
import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.hash import hash64_columns
from risingwave_tpu.state.hash_table import HashTable

CAP = 8192


def timeit(name, fn, n=50):
    jax.block_until_ready(fn())
    t0 = time.perf_counter()
    out = None
    for _ in range(n):
        out = fn()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{name:40s} {dt*1e3:9.3f} ms/call")
    return dt


def main():
    print("backend:", jax.default_backend())
    keys = jnp.asarray(np.random.randint(0, 50, CAP), jnp.int64)
    valid = jnp.ones((CAP,), jnp.bool_)

    h64 = jax.jit(lambda k: hash64_columns([k]))
    timeit("hash64 (1 i64 col)", lambda: h64(keys))

    for logsize in (14, 18):
        size = 1 << logsize
        table = HashTable.create([jnp.zeros((1,), jnp.int64)], size)
        lookup = jax.jit(lambda t, k: t.lookup_or_insert([k], valid))
        # warm inserts
        table2, *_ = lookup(table, keys)
        timeit(f"lookup_or_insert 2^{logsize}",
               lambda: lookup(table2, keys))

        vals = jnp.zeros((size,), jnp.int64)
        slots = jnp.asarray(np.random.randint(0, size, CAP), jnp.int32)
        contrib = jnp.ones((CAP,), jnp.int64)
        scat = jax.jit(lambda v, s, c: v.at[s].add(c, mode="drop"),
                       donate_argnums=(0,))
        v = vals
        def run_scat():
            nonlocal v
            v = scat(v, slots, contrib)
            return v
        timeit(f"scatter-add i64 into 2^{logsize}", run_scat)

        # flush-shaped ops
        dirty = jnp.zeros((size,), jnp.bool_).at[slots].set(True)
        nz = jax.jit(lambda d: jnp.nonzero(d, size=4096, fill_value=size))
        timeit(f"nonzero(dirty 2^{logsize}, size=4096)",
               lambda: nz(dirty))

    # while_loop iteration overhead: trivial 4-iter loop over [CAP]
    def loop(x):
        def body(c):
            v, it = c
            return v + 1, it + 1
        def cond(c):
            return c[1] < 4
        return jax.lax.while_loop(cond, body, (x, jnp.int32(0)))
    lo = jax.jit(loop)
    timeit("while_loop 4 trivial iters [8192]",
           lambda: lo(jnp.zeros((CAP,), jnp.int64)))


if __name__ == "__main__":
    main()
