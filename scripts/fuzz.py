"""Mini-fuzzer over the supported SQL surface (sqlsmith analog).

Ref: src/tests/sqlsmith/src/lib.rs — random query generation against
the full stack.  Here each generated query runs TWO ways and the
results must agree:

1. streaming: CREATE MATERIALIZED VIEW + FLUSH, read the MV
   (incremental maintenance through the jitted executors);
2. batch: the same query served directly over the base tables
   (one-shot snapshot through the same kernels, different dynamics —
   emission caps, retraction paths, and flush orders all differ).

A crash in either path or any result divergence is a failure.

Usage: JAX_PLATFORMS=cpu python scripts/fuzz.py [N] [seed]
Exit code 0 = all green.
"""

from __future__ import annotations

import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import risingwave_tpu  # noqa: F401,E402
from risingwave_tpu.sql import Engine  # noqa: E402
from risingwave_tpu.sql.planner import PlanError, PlannerConfig  # noqa: E402

N = int(sys.argv[1]) if len(sys.argv) > 1 else 500
SEED = int(sys.argv[2]) if len(sys.argv) > 2 else 20260730
R = random.Random(SEED)

T1_ROWS = [
    (
        R.randrange(0, 8),          # a: group key
        R.randrange(-20, 20),       # b
        R.randrange(0, 5),          # k: join key
        R.randrange(-1000, 1000),   # v
    )
    for _ in range(300)
]
T2_ROWS = [(k, R.randrange(-50, 50)) for k in range(5) for _ in range(3)]


def make_engine() -> Engine:
    return Engine(PlannerConfig(
        chunk_capacity=128,
        agg_table_size=1 << 10, agg_emit_capacity=1 << 9,
        join_table_size=1 << 10, join_bucket_cap=64,
        join_out_capacity=1 << 13,
        mv_table_size=1 << 11, mv_ring_size=1 << 13,
        topn_pool_size=1 << 10, topn_emit_capacity=1 << 9,
        minput_bucket_cap=64,
    ))


# -- random query generation -------------------------------------------


def gen_scalar(depth: int = 0) -> str:
    r = R.random()
    cols = ["a", "b", "v"]
    if depth > 2 or r < 0.35:
        return R.choice(cols)
    if r < 0.5:
        return str(R.randrange(-10, 10))
    if r < 0.75:
        op = R.choice(["+", "-", "*"])
        return f"({gen_scalar(depth + 1)} {op} {gen_scalar(depth + 1)})"
    if r < 0.85:
        return f"abs({gen_scalar(depth + 1)})"
    return (f"(CASE WHEN {gen_pred(depth + 1)} THEN "
            f"{gen_scalar(depth + 1)} ELSE {gen_scalar(depth + 1)} END)")


def gen_pred(depth: int = 0) -> str:
    r = R.random()
    if depth > 2 or r < 0.6:
        op = R.choice(["<", "<=", ">", ">=", "=", "<>"])
        return f"{gen_scalar(depth + 1)} {op} {gen_scalar(depth + 1)}"
    if r < 0.8:
        return f"({gen_pred(depth + 1)} AND {gen_pred(depth + 1)})"
    if r < 0.95:
        return f"({gen_pred(depth + 1)} OR {gen_pred(depth + 1)})"
    return f"{R.choice(['a', 'b', 'v'])} IN (1, 2, 3)"


def gen_agg() -> str:
    kind = R.choice(["count(*)", "sum", "min", "max", "count", "avg"])
    body = "count(*)" if kind == "count(*)" else f"{kind}({gen_scalar(1)})"
    if R.random() < 0.15:
        body += f" FILTER (WHERE {gen_pred(1)})"
    return body


def gen_query(i: int) -> tuple[str, str]:
    """Returns (kind, sql)."""
    shape = R.random()
    if shape < 0.45:
        # single-table GROUP BY aggregate
        n_aggs = R.randrange(1, 4)
        items = ["a AS g"] + [
            f"{gen_agg()} AS x{j}" for j in range(n_aggs)
        ]
        where = f" WHERE {gen_pred()}" if R.random() < 0.7 else ""
        having = f" HAVING count(*) >= {R.randrange(1, 3)}" \
            if R.random() < 0.3 else ""
        return "agg", (f"SELECT {', '.join(items)} FROM t1{where} "
                       f"GROUP BY a{having}")
    if shape < 0.7:
        # global aggregate
        items = [f"{gen_agg()} AS x{j}" for j in range(R.randrange(1, 4))]
        where = f" WHERE {gen_pred()}" if R.random() < 0.7 else ""
        return "agg", f"SELECT {', '.join(items)} FROM t1{where}"
    if shape < 0.9:
        # join + aggregate
        items = ["t1.k AS g", f"count(*) AS n",
                 f"sum({R.choice(['v', 'w', 'b'])}) AS s"]
        where = f" WHERE {gen_pred()}" if R.random() < 0.5 else ""
        return "join", (f"SELECT {', '.join(items)} FROM t1 "
                        f"JOIN t2 ON t1.k = t2.k{where} GROUP BY t1.k")
    # plain projection + filter
    items = [f"{gen_scalar()} AS p{j}" for j in range(R.randrange(1, 4))]
    return "proj", (f"SELECT a, b, v, {', '.join(items)} FROM t1 "
                    f"WHERE {gen_pred()}")


def normalize(rows, ndigits: int = 6) -> list:
    out = []
    for r in rows:
        vals = []
        for v in r:
            if v is None:
                vals.append(None)
            elif isinstance(v, float) or hasattr(v, "dtype") and \
                    "float" in str(getattr(v, "dtype", "")):
                vals.append(round(float(v), ndigits))
            else:
                try:
                    vals.append(int(v))
                except (TypeError, ValueError):
                    vals.append(str(v))
        out.append(tuple(vals))
    return sorted(out, key=lambda t: tuple(
        (x is None, str(type(x)), x) for x in t
    ))


def main() -> int:
    eng = make_engine()
    eng.execute("CREATE TABLE t1 (a BIGINT, b BIGINT, k BIGINT, "
                "v BIGINT)")
    eng.execute("CREATE TABLE t2 (k BIGINT, w BIGINT)")
    for i in range(0, len(T1_ROWS), 64):
        vals = ",".join(str(t) for t in T1_ROWS[i:i + 64])
        eng.execute(f"INSERT INTO t1 VALUES {vals}")
    vals = ",".join(str(t) for t in T2_ROWS)
    eng.execute(f"INSERT INTO t2 VALUES {vals}")
    eng.execute("FLUSH")

    ran = skipped = failed = 0
    for i in range(N):
        kind, sql = gen_query(i)
        mv = f"fz_{i}"
        try:
            try:
                eng.execute(f"CREATE MATERIALIZED VIEW {mv} AS {sql}")
            except (PlanError, ValueError) as e:
                skipped += 1
                continue
            eng.execute("FLUSH")
            streaming = eng.execute(f"SELECT * FROM {mv}")
            batch = eng.execute(sql)
            a, b = normalize(streaming), normalize(batch)
            if a != b:
                failed += 1
                print(f"[MISMATCH] {sql}")
                print(f"  streaming({len(a)}): {a[:5]}")
                print(f"  batch({len(b)}):     {b[:5]}")
            ran += 1
        except Exception as e:
            failed += 1
            print(f"[CRASH] {sql}\n  {type(e).__name__}: {e}")
        finally:
            try:
                eng.execute(f"DROP MATERIALIZED VIEW {mv}")
            except Exception:
                pass
        if (i + 1) % 50 == 0:
            print(f"... {i + 1}/{N} (ran {ran}, skipped {skipped}, "
                  f"failed {failed})", flush=True)

    print(f"fuzz: {ran} compared, {skipped} skipped (unsupported), "
          f"{failed} FAILED  [seed={SEED}]")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
