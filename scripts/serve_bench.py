"""Serving-tier bench: sustained concurrent reads DURING ingest.

The ISSUE 5 regression gate for Serve-lite: a 1-meta + 1-compute +
1-serving cluster (in-process) runs global barrier rounds (ingest +
per-barrier MV export + compaction + periodic vacuum) while reader
threads hammer the serving tier through the meta's router.  Asserted
floors (``--assert``):

- ZERO read errors across the whole window (reads pinned at committed
  epochs, replica leases vacuum-safe);
- sustained read throughput >= ``--min-reads-per-s``;
- block-cache hit ratio after warmup >= ``--min-hit-ratio`` (the
  serving tier serves from cache, not per-read SST I/O);
- the REPLICA carried the bulk of the reads (the owning worker left
  the read path — the point of the tier).

Usage:
    python scripts/serve_bench.py [--seconds 6] [--readers 4] [--assert]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def run(seconds: float = 6.0, readers: int = 4,
        vacuum_interval_s: float = 0.25,
        cache_blocks: int = 1024) -> dict:
    from risingwave_tpu.cluster import ComputeWorker, MetaService
    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.serve import ServingWorker

    cfg = RwConfig.from_dict({
        "streaming": {"chunk_size": 256},
        "state": {"agg_table_size": 1 << 10, "agg_emit_capacity": 256,
                  "mv_table_size": 1 << 10, "mv_ring_size": 1 << 12},
        "storage": {"checkpoint_keep_epochs": 4},
    })
    tmp = tempfile.mkdtemp(prefix="serve_bench_")
    meta = MetaService(tmp, heartbeat_timeout_s=10.0)
    meta.start(port=0, monitor=False)  # compactor ON, monitor manual
    addr = f"127.0.0.1:{meta.rpc_port}"
    worker = ComputeWorker(addr, tmp, config=cfg,
                           heartbeat_interval_s=0.5).start()
    meta.execute_ddl(
        "CREATE SOURCE t (k BIGINT, v BIGINT) "
        "WITH (connector='datagen')"
    )
    meta.execute_ddl(
        "CREATE MATERIALIZED VIEW bm AS "
        "SELECT k % 32 AS g, count(*) AS n, sum(v) AS s "
        "FROM t GROUP BY k % 32"
    )
    # warm the pipeline (first barrier pays jit compiles) and land the
    # first exports before the replica joins
    for _ in range(2):
        assert meta.tick(1)["committed"]
    replica = ServingWorker(addr, tmp, heartbeat_interval_s=0.1,
                            cache_blocks=cache_blocks).start()

    stop = threading.Event()
    errors: list = []
    reads = [0] * readers
    rounds = [0]
    last_vacuum = [time.monotonic()]

    def ingest_loop():
        while not stop.is_set():
            try:
                if meta.tick(1)["committed"]:
                    rounds[0] += 1
                if time.monotonic() - last_vacuum[0] \
                        > vacuum_interval_s:
                    meta.storage_vacuum()
                    last_vacuum[0] = time.monotonic()
            except Exception as e:  # noqa: BLE001
                errors.append(f"ingest: {e!r}")

    def read_loop(i: int):
        queries = [
            "SELECT g, n, s FROM bm",
            f"SELECT n FROM bm WHERE g = {i % 32}",
            "SELECT g, n FROM bm WHERE g >= 8 AND g < 24",
        ]
        while not stop.is_set():
            for sql in queries:
                try:
                    cols, rows = meta.serve(sql)
                    assert rows, "empty serving read"
                except Exception as e:  # noqa: BLE001
                    errors.append(repr(e))
            reads[i] += len(queries)

    threads = [threading.Thread(target=ingest_loop, daemon=True)]
    threads += [threading.Thread(target=read_loop, args=(i,),
                                 daemon=True) for i in range(readers)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    # warmup half, then reset cache counters so the hit-ratio floor
    # measures steady state, not cold fills
    time.sleep(seconds / 2)
    replica.view.cache.hits = 0
    replica.view.cache.misses = 0
    time.sleep(seconds / 2)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    elapsed = time.monotonic() - t0

    total_reads = sum(reads)
    summary = {
        "seconds": round(elapsed, 2),
        "readers": readers,
        "rounds_committed": rounds[0],
        "reads_total": total_reads,
        "reads_per_s": round(total_reads / elapsed, 1),
        "read_errors": len(errors),
        "errors_sample": errors[:3],
        "replica_reads": replica.reads_total,
        "replica_read_errors": replica.read_errors,
        "replica_share": round(
            replica.reads_total / max(total_reads, 1), 3),
        "cache_hit_ratio": round(replica.view.cache.hit_ratio(), 3),
        "gc_objects": int(meta.metrics.get("storage_gc_objects_total"))
        if _metric_exists(meta.metrics, "storage_gc_objects_total")
        else 0,
        "pinned_versions": meta.versions.pinned_count(),
    }
    replica.stop()
    worker.stop()
    meta.stop()
    return summary


def _metric_exists(m, name) -> bool:
    try:
        m.get(name)
        return True
    except KeyError:
        return False


def check(summary: dict, min_reads_per_s: float,
          min_hit_ratio: float, min_replica_share: float) -> list[str]:
    """The --assert floors; returns a list of violations (empty=pass)."""
    bad = []
    if summary["read_errors"] != 0:
        bad.append(f"read_errors={summary['read_errors']} != 0 "
                   f"({summary['errors_sample']})")
    if summary["replica_read_errors"] != 0:
        bad.append("replica_read_errors="
                   f"{summary['replica_read_errors']} != 0")
    if summary["reads_per_s"] < min_reads_per_s:
        bad.append(f"reads_per_s={summary['reads_per_s']} "
                   f"< {min_reads_per_s}")
    if summary["cache_hit_ratio"] < min_hit_ratio:
        bad.append(f"cache_hit_ratio={summary['cache_hit_ratio']} "
                   f"< {min_hit_ratio}")
    if summary["replica_share"] < min_replica_share:
        bad.append(f"replica_share={summary['replica_share']} "
                   f"< {min_replica_share}")
    if summary["rounds_committed"] < 1:
        bad.append("no rounds committed during the window")
    return bad


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seconds", type=float, default=6.0)
    p.add_argument("--readers", type=int, default=4)
    p.add_argument("--assert", dest="do_assert", action="store_true")
    p.add_argument("--min-reads-per-s", type=float, default=20.0)
    p.add_argument("--min-hit-ratio", type=float, default=0.5)
    p.add_argument("--min-replica-share", type=float, default=0.5)
    args = p.parse_args()

    summary = run(seconds=args.seconds, readers=args.readers)
    print(json.dumps(summary, indent=1))
    if args.do_assert:
        bad = check(summary, args.min_reads_per_s,
                    args.min_hit_ratio, args.min_replica_share)
        if bad:
            raise SystemExit("serve_bench FAILED:\n  " + "\n  ".join(bad))
        print("serve_bench: all floors PASSED")


if __name__ == "__main__":
    main()
