"""Serving-tier bench: memcached-class reads DURING ingest.

The ISSUE 10 regression gate for Serve-hot, grown from the ISSUE 5
serve-lite bench.  A 1-meta + 1-compute + 1-serving cluster
(in-process) runs global barrier rounds (ingest + per-barrier MV
export + compaction + periodic vacuum) while reader threads hammer
the serving tier through the meta's BATCHED router — repeat point
SELECTs served from the replica's epoch-keyed result cache, plus
first-class multi-gets sharing one sorted SstView pass.  Asserted
floors (``--assert``):

- ZERO read errors across the whole window, INCLUDING a replica
  hard-kill mid-window (a second replica joins, dies, and routing
  carries on);
- sustained read throughput >= ``--min-reads-per-s`` on the
  cached/batched workload (same-box target: >= 10k reads/s/replica,
  from 576 at round 8);
- p99.9 per-read latency <= ``--max-p999-ms`` (tail-latency gate per
  the Hazelcast-Jet 99.99th-percentile discipline);
- result-cache + block-cache hit ratios after warmup;
- epoch-advance invalidation: writes committed at e+1 are visible
  through the cache after the lease re-grant — ZERO stale rows,
  byte-identical to the owning worker;
- secondary-index lookups beat the full scan on the non-pk predicate
  workload with byte-identical results;
- filtered scans: a residual predicate + projection on a NON-indexed
  column evaluates inside the replica's block-walk merge scan —
  byte-identical to fetch-then-filter, rows provably elided
  server-side, and the shipped payload shrinks by at least the
  row-selectivity ratio;
- negative cache: repeated multi-gets for missing pks are absorbed
  per-vid after the first pass (hit-ratio floor).

Usage:
    python scripts/serve_bench.py [--seconds 6] [--readers 4]
        [--batch 64] [--assert]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

#: groups in the index-workload MV (full scan decodes this many rows;
#: the index path touches ~1)
KM_GROUPS = 512


def _percentile(samples: list, q: float) -> float:
    """Weighted percentile over (latency_s, n_items) batch samples —
    every read in a batch experiences the batch's latency."""
    if not samples:
        return 0.0
    ordered = sorted(samples)
    total = sum(n for _, n in ordered)
    target = q * total
    seen = 0
    for lat, n in ordered:
        seen += n
        if seen >= target:
            return lat
    return ordered[-1][0]


def run(seconds: float = 6.0, readers: int = 4, batch: int = 64,
        vacuum_interval_s: float = 0.25,
        cache_blocks: int = 4096,
        result_cache_bytes: int = 32 << 20) -> dict:
    from risingwave_tpu.cluster import ComputeWorker, MetaService
    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.serve import ServingWorker

    cfg = RwConfig.from_dict({
        "streaming": {"chunk_size": 256},
        "state": {"agg_table_size": 1 << 10, "agg_emit_capacity": 256,
                  "mv_table_size": 1 << 10, "mv_ring_size": 1 << 12},
        "storage": {"checkpoint_keep_epochs": 4},
    })
    tmp = tempfile.mkdtemp(prefix="serve_bench_")
    meta = MetaService(tmp, heartbeat_timeout_s=10.0)
    meta.start(port=0, monitor=False)  # compactor ON, monitor manual
    addr = f"127.0.0.1:{meta.rpc_port}"
    worker = ComputeWorker(addr, tmp, config=cfg,
                           heartbeat_interval_s=0.5).start()
    meta.execute_ddl(
        "CREATE SOURCE t (k BIGINT, v BIGINT) "
        "WITH (connector='datagen')"
    )
    meta.execute_ddl(
        "CREATE MATERIALIZED VIEW bm AS "
        "SELECT k % 32 AS g, count(*) AS n, sum(v) AS s "
        "FROM t GROUP BY k % 32"
    )
    # the index workload: a wider MV (full scan = KM_GROUPS rows) with
    # a secondary index on its non-pk aggregate column
    meta.execute_ddl(
        "CREATE MATERIALIZED VIEW km AS "
        f"SELECT k % {KM_GROUPS} AS kk, sum(v) AS s "
        f"FROM t GROUP BY k % {KM_GROUPS}"
    )
    meta.execute_ddl("CREATE INDEX km_s ON km(s)")
    # the filtered-scan workload: NO index on fm, so a predicate on
    # its aggregate column must run as a residual filter inside the
    # replica's block-walk evaluator (the pushdown plane)
    meta.execute_ddl(
        "CREATE MATERIALIZED VIEW fm AS "
        f"SELECT k % {KM_GROUPS} AS kk, sum(v) AS s "
        f"FROM t GROUP BY k % {KM_GROUPS}"
    )
    # the invalidation probe: a DML-fed table + MV the probe writes
    # through committed rounds
    meta.execute_ddl("CREATE TABLE pt (k BIGINT, v BIGINT)")
    meta.execute_ddl(
        "CREATE MATERIALIZED VIEW pm AS "
        "SELECT k, sum(v) AS s FROM pt GROUP BY k"
    )
    # warm the pipeline (first barrier pays jit compiles) and land the
    # first exports before the replica joins
    for _ in range(2):
        assert meta.tick(1)["committed"]
    replica = ServingWorker(
        addr, tmp, heartbeat_interval_s=0.1,
        cache_blocks=cache_blocks,
        result_cache_bytes=result_cache_bytes,
    ).start()

    stop = threading.Event()
    errors: list = []
    reads = [0] * readers
    lat_lock = threading.Lock()
    latencies: list = []  # (batch_latency_s, n_items)
    rounds = [0]
    last_vacuum = [time.monotonic()]

    def ingest_loop():
        while not stop.is_set():
            try:
                if meta.tick(1)["committed"]:
                    rounds[0] += 1
                meta.check_heartbeats()  # monitor=False: reap manually
                if time.monotonic() - last_vacuum[0] \
                        > vacuum_interval_s:
                    meta.storage_vacuum()
                    last_vacuum[0] = time.monotonic()
            except Exception as e:  # noqa: BLE001
                errors.append(f"ingest: {e!r}")

    def read_loop(i: int):
        it = 0
        while not stop.is_set():
            it += 1
            try:
                if it % 4 == 0:
                    # first-class multi-get: one MV + N pks, one frame
                    t0 = time.perf_counter()
                    cols, rows = meta.serve_multi_get(
                        "bm", [[g] for g in range(16)],
                        cols=["g", "n"],
                    )
                    dt = time.perf_counter() - t0
                    assert rows, "empty multi-get"
                    n = 16
                else:
                    qs = [
                        f"SELECT g, n, s FROM bm WHERE g = "
                        f"{(i + j) % 32}"
                        for j in range(batch)
                    ]
                    t0 = time.perf_counter()
                    res = meta.serve_batch(qs)
                    dt = time.perf_counter() - t0
                    assert all(r[1] for r in res), "empty batch item"
                    n = len(qs)
                with lat_lock:
                    latencies.append((dt, n))
                reads[i] += n
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

    threads = [threading.Thread(target=ingest_loop, daemon=True)]
    threads += [threading.Thread(target=read_loop, args=(i,),
                                 daemon=True) for i in range(readers)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    # warmup half, then reset cache + latency counters so floors
    # measure steady state, not cold fills / first-compile stalls
    time.sleep(seconds / 2)
    replica.view.cache.hits = 0
    replica.view.cache.misses = 0
    replica.result_cache.hits = 0
    replica.result_cache.misses = 0
    with lat_lock:
        latencies.clear()
    reads_mark = sum(reads)
    t_mark = time.monotonic()
    # a second replica joins, takes reads, and HARD-dies mid-window —
    # routing must carry every read with zero errors
    replica2 = ServingWorker(
        addr, tmp, heartbeat_interval_s=0.1,
        cache_blocks=cache_blocks,
        result_cache_bytes=result_cache_bytes,
    ).start()
    time.sleep(seconds / 4)
    replica2_reads = replica2.reads_total
    replica2._stop.set()
    replica2._server.stop()   # sockets die, no unregister — a kill
    replica2._server = None
    time.sleep(seconds / 4)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    elapsed = time.monotonic() - t_mark
    measured_reads = sum(reads) - reads_mark

    # replica counters BEFORE the probe/index phases (those read the
    # replica directly and must not inflate the routed-share ratio)
    replica_reads_window = replica.reads_total
    with lat_lock:
        lat = list(latencies)
    p50 = _percentile(lat, 0.50) * 1e3
    p99 = _percentile(lat, 0.99) * 1e3
    p999 = _percentile(lat, 0.999) * 1e3

    # -- epoch-advance invalidation probe: a write committed at e+1
    # must be visible THROUGH the cache after the lease re-grant,
    # byte-identical to the owning worker — zero stale rows
    stale_rows = 0
    probe_errors: list = []
    for i in range(4):
        k, v = 9000 + i, 7 * (i + 1)
        try:
            meta.execute_ddl(f"INSERT INTO pt VALUES ({k}, {v})")
            deadline = time.monotonic() + 30
            while not meta.tick(1)["committed"]:
                if time.monotonic() > deadline:
                    raise RuntimeError("probe round never committed")
            sql = f"SELECT s FROM pm WHERE k = {k}"
            # prime the cache at the PREVIOUS vid, then re-read after
            # the commit: the re-grant re-keys the cache by
            # construction, so the fresh row must appear
            (cols, rows), = meta.serve_batch([sql])
            with meta._lock:
                job = meta.jobs[meta._mv_to_job["pm"]]
                w = meta.workers[job.worker_id]
                pin = job.pinned_epoch
            owner = w.client.call("serve", sql=sql, query_epoch=pin)
            owner_rows = [tuple(r) for r in owner["rows"]]
            if rows != owner_rows or rows != [(v,)]:
                stale_rows += 1
                probe_errors.append(
                    f"k={k}: serve={rows} owner={owner_rows} "
                    f"want={[(v,)]}"
                )
        except Exception as e:  # noqa: BLE001
            probe_errors.append(repr(e))
            stale_rows += 1

    # -- secondary index vs full scan (quiesced): byte-identical
    # results, index faster on the non-pk predicate workload
    index_identical = True
    index_speedup = 0.0
    try:
        rc_budget = replica.result_cache.max_bytes
        replica.result_cache.max_bytes = 0  # measure UNCACHED costs
        _, km_rows, _ = replica.read("SELECT kk, s FROM km")
        svals = [r[1] for r in km_rows[:32]]
        # warm both paths once (block cache fills either way)
        replica.read(f"SELECT kk, s FROM km WHERE s = {svals[0]}")
        t0 = time.perf_counter()
        for s in svals:
            _, got, _ = replica.read(
                f"SELECT kk, s FROM km WHERE s = {s}"
            )
            want = sorted(r for r in km_rows if r[1] == s)
            if sorted(got) != want:
                index_identical = False
        t_index = time.perf_counter() - t0
        t0 = time.perf_counter()
        for s in svals:
            _, allr, _ = replica.read("SELECT kk, s FROM km")
            _ = [r for r in allr if r[1] == s]
        t_scan = time.perf_counter() - t0
        index_speedup = t_scan / max(t_index, 1e-9)
        replica.result_cache.max_bytes = rc_budget
    except Exception as e:  # noqa: BLE001
        index_identical = False
        probe_errors.append(f"index: {e!r}")

    # -- filtered scan (quiesced): a residual predicate + projection
    # on a NON-indexed column evaluates per block inside the replica's
    # merge scan.  The win of near-data eval is shipped-data
    # reduction: the pushdown response must shrink (in bytes) by at
    # least the row-selectivity ratio, with byte-identical rows vs
    # fetch-then-filter
    import pickle as _pickle
    filtered_identical = True
    filtered_data_reduction = 0.0
    filtered_byte_reduction = 0.0
    filtered_rows_elided = 0
    try:
        rc_budget = replica.result_cache.max_bytes
        replica.result_cache.max_bytes = 0  # measure UNCACHED costs
        _, full_rows, _ = replica.read("SELECT kk, s FROM fm")
        svals = sorted(r[1] for r in full_rows)
        thresh = svals[(len(svals) * 9) // 10]  # ~10% selective
        elided0 = _metric_get(replica.metrics,
                              "pushdown_rows_elided_total",
                              where="replica")
        _, sel_rows, _ = replica.read(
            f"SELECT kk, s FROM fm WHERE s >= {thresh}"
        )
        filtered_rows_elided = int(_metric_get(
            replica.metrics, "pushdown_rows_elided_total",
            where="replica") - elided0)
        want = sorted(r for r in full_rows if r[1] >= thresh)
        filtered_identical = sorted(sel_rows) == want
        bytes_full = len(_pickle.dumps(full_rows))
        bytes_sel = len(_pickle.dumps(sel_rows))
        filtered_data_reduction = len(full_rows) / max(len(sel_rows), 1)
        filtered_byte_reduction = bytes_full / max(bytes_sel, 1)
        replica.result_cache.max_bytes = rc_budget
    except Exception as e:  # noqa: BLE001
        filtered_identical = False
        probe_errors.append(f"filtered: {e!r}")

    # -- negative cache: repeated multi-gets for pks that do not exist
    # must stop costing SstView passes after the first round — the
    # per-vid negative cache absorbs them until the next re-grant
    neg_hit_ratio = 0.0
    neg_entries = 0
    try:
        missing = [[10_000_000 + j] for j in range(16)]
        passes, lookups = 6, 0
        h0 = replica.neg_cache.hits
        for _ in range(passes):
            _, rows_m, _ = replica.multi_get("bm", missing,
                                             cols=["g", "n"])
            assert not rows_m, f"phantom rows for missing pks: {rows_m}"
            lookups += len(missing)
        neg_hit_ratio = (replica.neg_cache.hits - h0) / max(lookups, 1)
        neg_entries = len(replica.neg_cache)
    except Exception as e:  # noqa: BLE001
        probe_errors.append(f"negcache: {e!r}")

    total_reads = sum(reads)
    summary = {
        "seconds": round(elapsed, 2),
        "readers": readers,
        "batch": batch,
        "rounds_committed": rounds[0],
        "reads_total": total_reads,
        "reads_per_s": round(measured_reads / elapsed, 1),
        "latency_ms": {"p50": round(p50, 3), "p99": round(p99, 3),
                       "p999": round(p999, 3)},
        "read_errors": len(errors),
        "errors_sample": errors[:3],
        "replica_reads": replica_reads_window,
        "replica_read_errors": replica.read_errors
        + replica2.read_errors,
        "replica2_reads": replica2_reads,
        "replica_share": round(
            min(1.0, (replica_reads_window + replica2.reads_total)
                / max(total_reads, 1)), 3),
        "cache_hit_ratio": round(replica.view.cache.hit_ratio(), 3),
        "result_cache_hit_ratio": round(
            replica.result_cache.hit_ratio(), 3),
        "result_cache_bytes": replica.result_cache.bytes,
        "stale_rows": stale_rows,
        "probe_errors": probe_errors[:3],
        "index_identical": index_identical,
        "index_speedup": round(index_speedup, 2),
        "filtered_identical": filtered_identical,
        "filtered_data_reduction": round(filtered_data_reduction, 2),
        "filtered_byte_reduction": round(filtered_byte_reduction, 2),
        "filtered_rows_elided": filtered_rows_elided,
        "negcache_hit_ratio": round(neg_hit_ratio, 3),
        "negcache_entries": neg_entries,
        "warmup_replays": replica.warmup_replays,
        "gc_objects": int(meta.metrics.get("storage_gc_objects_total"))
        if _metric_exists(meta.metrics, "storage_gc_objects_total")
        else 0,
        "pinned_versions": meta.versions.pinned_count(),
    }
    replica.stop()
    worker.stop()
    meta.stop()
    return summary


def _metric_exists(m, name) -> bool:
    try:
        m.get(name)
        return True
    except KeyError:
        return False


def _metric_get(m, name, **labels) -> float:
    try:
        return m.get(name, **labels)
    except KeyError:
        return 0.0


def write_artifact(summary: dict) -> None:
    """bench.py-shaped JSON line (SERVE_BENCH.json next to
    MULTICHIP_BENCH.json) so the driver artifact set carries the
    serving-tier numbers + latency percentiles."""
    rec = {
        "benchmark": "serve_hot",
        "value": summary["reads_per_s"],
        "unit": "reads/s",
        "latency_ms": summary["latency_ms"],
        "queries": {
            "cached_batch": {"value": summary["reads_per_s"],
                             "cpu_baseline": None,
                             "vs_baseline": None},
            "index_lookup": {"value": summary["index_speedup"],
                             "unit": "x_vs_full_scan"},
            "filtered_scan": {
                "value": summary["filtered_byte_reduction"],
                "unit": "x_bytes_vs_fetch_then_filter"},
            "negative_cache": {"value": summary["negcache_hit_ratio"],
                               "unit": "hit_ratio"},
        },
        "invariants": {
            "read_errors": summary["read_errors"],
            "stale_rows": summary["stale_rows"],
            "index_identical": summary["index_identical"],
            "filtered_identical": summary["filtered_identical"],
            "filtered_rows_elided": summary["filtered_rows_elided"],
            "rounds_committed": summary["rounds_committed"],
        },
        "errors": summary["errors_sample"] or None,
        "blocker": None,
    }
    try:
        out = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "SERVE_BENCH.json",
        )
        with open(out, "w") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
    except OSError:
        pass


def check(summary: dict, min_reads_per_s: float,
          min_hit_ratio: float, min_replica_share: float,
          max_p999_ms: float = 500.0,
          min_result_hit_ratio: float = 0.5,
          min_index_speedup: float = 1.0,
          min_negcache_ratio: float = 0.5) -> list[str]:
    """The --assert floors; returns a list of violations (empty=pass)."""
    bad = []
    if summary["read_errors"] != 0:
        bad.append(f"read_errors={summary['read_errors']} != 0 "
                   f"({summary['errors_sample']})")
    if summary["replica_read_errors"] != 0:
        bad.append("replica_read_errors="
                   f"{summary['replica_read_errors']} != 0")
    if summary["reads_per_s"] < min_reads_per_s:
        bad.append(f"reads_per_s={summary['reads_per_s']} "
                   f"< {min_reads_per_s}")
    if summary["latency_ms"]["p999"] > max_p999_ms:
        bad.append(f"p99.9={summary['latency_ms']['p999']}ms "
                   f"> {max_p999_ms}ms")
    if summary["cache_hit_ratio"] < min_hit_ratio:
        bad.append(f"cache_hit_ratio={summary['cache_hit_ratio']} "
                   f"< {min_hit_ratio}")
    if summary["result_cache_hit_ratio"] < min_result_hit_ratio:
        bad.append(
            "result_cache_hit_ratio="
            f"{summary['result_cache_hit_ratio']} "
            f"< {min_result_hit_ratio}")
    if summary["replica_share"] < min_replica_share:
        bad.append(f"replica_share={summary['replica_share']} "
                   f"< {min_replica_share}")
    if summary["stale_rows"] != 0:
        bad.append(f"stale_rows={summary['stale_rows']} != 0 "
                   f"({summary['probe_errors']})")
    if not summary["index_identical"]:
        bad.append(
            f"index results not byte-identical "
            f"({summary['probe_errors']})")
    if summary["index_speedup"] < min_index_speedup:
        bad.append(f"index_speedup={summary['index_speedup']}x "
                   f"< {min_index_speedup}x vs full scan")
    if not summary["filtered_identical"]:
        bad.append("filtered-scan results not byte-identical to "
                   f"fetch-then-filter ({summary['probe_errors']})")
    if summary["filtered_rows_elided"] <= 0:
        bad.append("filtered scan elided no rows server-side "
                   "(pushdown evaluator did not run)")
    # near-data eval must shrink the shipped payload by at least the
    # row-selectivity ratio (small tolerance for per-row framing)
    if summary["filtered_byte_reduction"] \
            < 0.9 * summary["filtered_data_reduction"]:
        bad.append(
            f"filtered_byte_reduction="
            f"{summary['filtered_byte_reduction']}x < 0.9 * "
            f"data_reduction={summary['filtered_data_reduction']}x")
    if summary["negcache_hit_ratio"] < min_negcache_ratio:
        bad.append(f"negcache_hit_ratio={summary['negcache_hit_ratio']}"
                   f" < {min_negcache_ratio}")
    if summary["rounds_committed"] < 1:
        bad.append("no rounds committed during the window")
    return bad


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seconds", type=float, default=6.0)
    p.add_argument("--readers", type=int, default=4)
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--assert", dest="do_assert", action="store_true")
    p.add_argument("--min-reads-per-s", type=float, default=10000.0)
    p.add_argument("--max-p999-ms", type=float, default=500.0)
    p.add_argument("--min-hit-ratio", type=float, default=0.5)
    p.add_argument("--min-result-hit-ratio", type=float, default=0.5)
    p.add_argument("--min-replica-share", type=float, default=0.5)
    p.add_argument("--min-index-speedup", type=float, default=1.0)
    p.add_argument("--min-negcache-ratio", type=float, default=0.5)
    args = p.parse_args()

    summary = run(seconds=args.seconds, readers=args.readers,
                  batch=args.batch)
    print(json.dumps(summary, indent=1))
    write_artifact(summary)
    if args.do_assert:
        bad = check(summary, args.min_reads_per_s,
                    args.min_hit_ratio, args.min_replica_share,
                    max_p999_ms=args.max_p999_ms,
                    min_result_hit_ratio=args.min_result_hit_ratio,
                    min_index_speedup=args.min_index_speedup,
                    min_negcache_ratio=args.min_negcache_ratio)
        if bad:
            raise SystemExit("serve_bench FAILED:\n  " + "\n  ".join(bad))
        print("serve_bench: all floors PASSED")


if __name__ == "__main__":
    main()
