"""Profile + regression-gate the incremental snapshot / async upload path.

The round-6 attribution named the every-8-checkpoints full-tree
``_snapshot_copy`` (~1.3 GB device copy, 6-8 s stalls ≈ half the q8
window) as the single biggest remaining lever.  Round 7 replaced it
with the ShadowSnapshot (digest-diff + dirty-block scatter, one async
dispatch) and moved durable persistence to a background uploader.
This script times the pieces and, with ``--assert``, turns the
structural guarantees into hard failures:

  - snapshot COPY traffic scales with dirty blocks, not state size
    (the copy component of a 0.5%-dirty update is a small fraction of
    the all-dirty update's);
  - a dirty-block update is not slower than the bare full copy it
    replaced (it also buys the digest the durable store reuses);
  - the steady barrier path — chunks, barriers, AND shadow-snapshot
    barriers — performs ZERO synchronous device→host transfers
    (enforced with jax's transfer guard, which raises on any d2h);
  - the upload queue is bounded under sustained load: the barrier loop
    write-stalls rather than queueing unacked epochs past the window;
  - recovery equivalence: restore from the shadow and from the
    async-uploaded durable chain are byte-identical to the live state
    at the sealed epoch.

Usage:
  JAX_PLATFORMS=cpu python scripts/profile_snapshot.py            # timings
  JAX_PLATFORMS=cpu python scripts/profile_snapshot.py --assert   # gate
  ... --assert --small    # reduced sizes (the CI/pytest wrapper)
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import risingwave_tpu  # noqa: F401,E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from risingwave_tpu.sql import Engine  # noqa: E402
from risingwave_tpu.sql.planner import PlannerConfig  # noqa: E402
from risingwave_tpu.stream.runtime import _snapshot_copy  # noqa: E402
from risingwave_tpu.stream.shadow import ShadowSnapshot  # noqa: E402


def _median_time(fn, n=3) -> float:
    out = []
    for _ in range(n):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        out.append(time.perf_counter() - t0)
    return sorted(out)[n // 2]


def make_tree(small: bool):
    # big enough that copy/digest dwarf per-dispatch noise on 1 core
    n = 1 << (23 if small else 24)
    leaves = tuple(
        jnp.arange(n, dtype=jnp.int64) * (i + 1) for i in range(4)
    )
    jax.block_until_ready(leaves)
    return leaves, n


def dirty_fraction(tree, n, frac):
    """Contiguous dirty prefix (the bump-allocator / ring-cursor write
    pattern the streaming state actually produces)."""
    k = max(1, int(n * frac))
    out = tuple(x.at[:k].add(1) for x in tree)
    jax.block_until_ready(out)
    return out


def q8_engine(small: bool) -> Engine:
    cap = 1024 if small else 8192
    eng = Engine(PlannerConfig(
        chunk_capacity=cap,
        agg_table_size=1 << 12, agg_emit_capacity=1024,
        join_left_table_size=1 << 14, join_right_table_size=1 << 14,
        join_pool_size=1 << 18, join_out_capacity=1 << 10,
        mv_table_size=1 << 12, mv_ring_size=1 << 16,
    ))
    eng.execute("""
    CREATE SOURCE person (
        id BIGINT, name VARCHAR, date_time TIMESTAMP,
        WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
    ) WITH (connector = 'nexmark', nexmark.table = 'person',
            nexmark.event.rate = '1000000');
    CREATE SOURCE auction (
        id BIGINT, seller BIGINT, reserve BIGINT, expires TIMESTAMP,
        date_time TIMESTAMP,
        WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
    ) WITH (connector = 'nexmark', nexmark.table = 'auction',
            nexmark.event.rate = '1000000');
    CREATE MATERIALIZED VIEW bench_mv AS
    SELECT p.id AS id, p.name AS name, a.reserve AS reserve
    FROM TUMBLE(person, date_time, INTERVAL '1' SECOND) p
    JOIN TUMBLE(auction, date_time, INTERVAL '1' SECOND) a
    ON p.id = a.seller AND p.window_start = a.window_start;
    """)
    return eng


def _states_host(job):
    return jax.device_get(job.states)


def _tree_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    if len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        if x.shape != y.shape or x.dtype != y.dtype:
            return False
        if x.dtype.kind == "f":
            if not np.array_equal(x, y, equal_nan=True):
                return False
        elif not np.array_equal(x, y):
            return False
    return True


# ----------------------------------------------------------------------
def check_dirty_scaling(small: bool, failures: list[str]) -> dict:
    tree, n = make_tree(small)
    t_copy = _median_time(lambda: _snapshot_copy(tree))

    sh = ShadowSnapshot(tree)
    jax.block_until_ready(sh.leaves)
    cur = tree

    def upd(frac):
        nonlocal cur
        cur = dirty_fraction(cur, n, frac)
        t0 = time.perf_counter()
        sh.update(cur)
        jax.block_until_ready(sh.leaves)
        return time.perf_counter() - t0

    upd(0.001)  # compile every rung once
    upd(0.05)
    upd(1.0)
    t_clean = _median_time(lambda: (sh.update(cur), sh.leaves)[1])
    t_small = sorted(upd(0.005) for _ in range(3))[1]
    t_full = sorted(upd(1.0) for _ in range(3))[1]

    if not _tree_equal(sh.restore(), cur):
        failures.append("dirty-scaling: shadow restore != live tree")
    copy_small = max(t_small - t_clean, 0.0)
    copy_full = max(t_full - t_clean, 1e-9)
    # guard bands absorb 1-core scheduling noise on sub-second runs
    if copy_small > max(0.35 * copy_full, 0.025):
        failures.append(
            f"dirty-scaling: 0.5%-dirty copy component {copy_small:.3f}s"
            f" is not a small fraction of all-dirty {copy_full:.3f}s — "
            "snapshot copy traffic no longer scales with dirty blocks"
        )
    if t_small > 1.6 * t_copy + 0.05:
        failures.append(
            f"dirty-scaling: 0.5%-dirty update {t_small:.3f}s vs bare "
            f"full copy {t_copy:.3f}s — the incremental snapshot lost "
            "to the copy it replaced"
        )
    return {"full_copy": t_copy, "update_clean": t_clean,
            "update_0.5%": t_small, "update_all_dirty": t_full}


def check_no_sync_readback(small: bool, failures: list[str]) -> None:
    eng = q8_engine(small)
    eng.execute(
        "ALTER SYSTEM SET maintenance_interval_checkpoints = 1000000"
    )
    eng.execute("ALTER SYSTEM SET snapshot_interval_checkpoints = 4")
    # warm: compiles + the first shadow snapshot (build + re-base)
    eng.tick(barriers=9, chunks_per_barrier=2)
    try:
        with jax.transfer_guard_device_to_host("disallow"):
            # covers plain barriers AND one snapshot barrier
            eng.tick(barriers=4, chunks_per_barrier=2)
    except Exception as e:  # noqa: BLE001
        failures.append(
            "sync-readback: steady barrier path performed a "
            f"synchronous device→host transfer: {e!r:.300}"
        )


def check_bounded_queue(small: bool, tmp: str, failures: list[str],
                        ) -> dict:
    eng = q8_engine(True)  # small state: upload latency dominates
    import shutil
    os.makedirs(tmp, exist_ok=True)
    from risingwave_tpu.storage.checkpoint_store import CheckpointStore
    store = CheckpointStore(os.path.join(tmp, "ckpt"))
    real_put = store.store.put

    def slow_put(key, data):
        time.sleep(0.05)
        real_put(key, data)

    store.store.put = slow_put
    job = eng.jobs[0]
    job.checkpoint_store = store
    job.checkpoint_frequency = 1
    job.snapshot_interval = 1
    job.maintenance_interval = 1 << 30
    job.upload_window = 2
    max_depth = 0
    for _ in range(12):
        job.run_chunks(1)
        job.inject_barrier()
        max_depth = max(max_depth, job.upload_queue_depth())
    window_bound = job.upload_window + 1  # +1: the epoch just sealed
    if max_depth > window_bound:
        failures.append(
            f"bounded-queue: upload queue reached {max_depth} epochs "
            f"(window {job.upload_window}) — the write stall is not "
            "bounding in-flight checkpoints"
        )
    job.drain_uploads()
    if job.committed_epoch != job.sealed_epoch:
        failures.append(
            "bounded-queue: drain left committed "
            f"{job.committed_epoch} != sealed {job.sealed_epoch}"
        )
    if store.committed_epoch(job.name) != job.sealed_epoch:
        failures.append(
            "bounded-queue: durable manifest epoch "
            f"{store.committed_epoch(job.name)} != sealed "
            f"{job.sealed_epoch}"
        )
    shutil.rmtree(tmp, ignore_errors=True)
    return {"max_queue_depth": max_depth,
            "stall_seconds": round(job.stall_seconds, 3)}


def check_recovery_equivalence(small: bool, tmp: str,
                               failures: list[str]) -> None:
    import shutil

    # in-memory: shadow restore must be byte-identical to live state
    eng = q8_engine(True)
    eng.execute("ALTER SYSTEM SET snapshot_interval_checkpoints = 2")
    eng.tick(barriers=4, chunks_per_barrier=2)
    job = eng.jobs[0]
    live = _states_host(job)
    job.recover()
    if not _tree_equal(job.states, live):
        failures.append(
            "recovery: in-memory shadow restore != live state at the "
            "sealed epoch"
        )

    # durable: the async-uploaded chain must reconstruct byte-identical
    os.makedirs(tmp, exist_ok=True)
    eng2 = Engine(PlannerConfig(
        chunk_capacity=256, agg_table_size=1 << 10,
        agg_emit_capacity=256, mv_table_size=1 << 10,
        mv_ring_size=1 << 12,
    ), data_dir=os.path.join(tmp, "node"))
    eng2.execute("""
        CREATE SOURCE bid (
            auction BIGINT, bidder BIGINT, price BIGINT,
            channel VARCHAR, url VARCHAR, date_time TIMESTAMP
        ) WITH (connector = 'nexmark', nexmark.table = 'bid');
        CREATE MATERIALIZED VIEW q7 AS
        SELECT window_start, max(price) AS max_price, count(*) AS bids
        FROM TUMBLE(bid, date_time, INTERVAL '1' SECOND)
        GROUP BY window_start;
    """)
    eng2.tick(barriers=5, chunks_per_barrier=1)
    job2 = eng2.jobs[0]
    live2 = _states_host(job2)
    sealed = job2.sealed_epoch
    loaded = eng2.checkpoint_store.load(job2.name)
    if loaded is None or loaded[0] != sealed:
        failures.append(
            f"recovery: durable chain missing sealed epoch {sealed}"
        )
    elif not _tree_equal(loaded[1], live2):
        failures.append(
            "recovery: async-uploaded durable checkpoint != live state"
        )
    job2.recover()
    if not _tree_equal(job2.states, live2):
        failures.append(
            "recovery: recover() from durable chain != live state"
        )
    shutil.rmtree(tmp, ignore_errors=True)


def run_assert(small: bool) -> int:
    failures: list[str] = []
    scaling = check_dirty_scaling(small, failures)
    check_no_sync_readback(small, failures)
    queue = check_bounded_queue(
        small, "/tmp/_profile_snapshot_q", failures
    )
    check_recovery_equivalence(
        small, "/tmp/_profile_snapshot_r", failures
    )
    if failures:
        print("profile_snapshot --assert: FAIL", flush=True)
        for f in failures:
            print(f"  - {f}", flush=True)
        return 1
    print(
        "profile_snapshot --assert: OK — "
        f"copy {scaling['full_copy'] * 1e3:.0f}ms, "
        f"0.5%-dirty update {scaling['update_0.5%'] * 1e3:.0f}ms "
        f"(clean {scaling['update_clean'] * 1e3:.0f}ms, all-dirty "
        f"{scaling['update_all_dirty'] * 1e3:.0f}ms); zero sync d2h "
        f"on the steady path; max upload queue "
        f"{queue['max_queue_depth']} (stalled "
        f"{queue['stall_seconds']}s); recovery byte-identical",
        flush=True,
    )
    return 0


def main():
    small = "--small" in sys.argv
    if "--assert" in sys.argv:
        sys.exit(run_assert(small))
    failures: list[str] = []
    scaling = check_dirty_scaling(small, failures)
    for k, v in scaling.items():
        print(f"{k:20s} {v * 1e3:9.2f} ms")
    for f in failures:
        print(f"note: {f}")


if __name__ == "__main__":
    main()
