"""Trace-lite acceptance harness: cross-role round traces + overhead.

Two gates for the observability plane (ISSUE 14):

1. **Round-trace assembly** — a 4-role subprocess cluster (1 meta +
   2 compute + 1 serving, real processes) runs N driver-paced rounds;
   for EVERY committed round ``ctl cluster trace`` must assemble one
   complete cross-role span tree: the meta round span parenting the
   worker barrier-phase spans (dispatch / seal / mv_export), the
   uploader's prepare/commit spans, a meta commit span that covers
   every worker seal span, and (for rounds after the first serving
   read) at least one sampled serving read span.  The ``--chrome``
   export must be loadable ``trace_event`` JSON, and the meta's
   ``/metrics`` HTTP endpoint plus the aggregated ``cluster_metrics``
   scrape must carry ``barrier_phase_seconds`` for the live job.

2. **Overhead contract** — tracing enabled vs ``trace_sample_n=0``
   A/B on an in-process q1-style bench loop must differ by < 2%
   (medians over interleaved segments; disabled tracing is a null-
   object fast path, not a branch per span).

Run standalone (prints one JSON summary line)::

    python scripts/trace_report.py --rounds 6 --assert

or the ``slow``-marked pytest wrapper (tests/test_trace_report.py).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, ".")  # repo root

CONFIG = {
    "streaming": {"chunk_size": 256},
    "state": {"agg_table_size": 1 << 10, "agg_emit_capacity": 256,
              "mv_table_size": 1 << 10, "mv_ring_size": 1 << 12},
    "storage": {"checkpoint_keep_epochs": 4},
}

DDL = [
    """CREATE SOURCE bid (
        auction BIGINT, bidder BIGINT, price BIGINT,
        channel VARCHAR, url VARCHAR, date_time TIMESTAMP
    ) WITH (connector = 'nexmark', nexmark.table = 'bid')""",
    """CREATE MATERIALIZED VIEW qcnt AS
    SELECT auction % 16 AS a, count(*) AS n, sum(price) AS vol
    FROM bid GROUP BY auction % 16""",
]

READ = "SELECT a, n, vol FROM qcnt"

#: span names the meta records on the barrier path of every round
META_SPANS = {"round", "barrier", "await_durable", "commit"}
#: span names the owning worker records inside its barrier handling
WORKER_SPANS = {"dispatch", "seal", "mv_export"}


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env() -> dict:
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"))
    env.pop("RWT_FAULTS", None)
    return env


def _spawn(role: str, data_dir: str, rpc_port: int,
           metrics_port: int = 0, idx: int = 0):
    argv = [sys.executable, "-m", "risingwave_tpu.server",
            "--role", role, "--data-dir", data_dir,
            "--trace-sample-n", "1"]
    if role == "meta":
        argv += ["--port", str(_free_port()),
                 "--rpc-port", str(rpc_port),
                 "--heartbeat-timeout", "3.0",
                 "--barrier-interval-ms", "0",  # driver-paced rounds
                 "--scrub-interval", "0"]
        if metrics_port:
            argv += ["--metrics-port", str(metrics_port)]
    else:
        argv += ["--meta", f"127.0.0.1:{rpc_port}",
                 "--heartbeat-interval", "0.25"]
        if role == "compute":
            argv += ["--config-json", json.dumps(CONFIG)]
    return subprocess.Popen(
        argv, stdout=subprocess.DEVNULL,
        stderr=open(os.path.join(data_dir, f"{role}{idx}.log"), "wb"),
        env=_env(),
    )


class MetaDriver:
    """Patient RPC driver (scripts/chaos_campaign.py idiom)."""

    def __init__(self, rpc_port: int):
        from risingwave_tpu.cluster.rpc import RpcClient

        self.client = RpcClient("127.0.0.1", rpc_port, timeout=120.0,
                                src="driver", dst="meta")

    def call(self, method: str, deadline_s: float = 120.0, **params):
        from risingwave_tpu.cluster.rpc import RpcError

        deadline = time.monotonic() + deadline_s
        while True:
            try:
                return self.client.call(method, **params)
            except RpcError:
                raise
            except (ConnectionError, OSError):
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.1)

    def close(self) -> None:
        self.client.close()


def _span_window(spans: list, name: str) -> "tuple | None":
    picked = [s for s in spans if s["name"] == name]
    if not picked:
        return None
    return (min(s["ts"] for s in picked),
            max(s["ts"] + s["dur"] for s in picked))


def run_cluster(rounds: int = 6, workers: int = 2,
                chrome: str | None = None,
                data_dir: str | None = None) -> dict:
    """Gate 1: the 4-role round-trace assembly run."""
    data_dir = data_dir or tempfile.mkdtemp(prefix="trace_report_")
    rpc_port = _free_port()
    metrics_port = _free_port()
    procs = [_spawn("meta", data_dir, rpc_port,
                    metrics_port=metrics_port)]
    procs += [_spawn("compute", data_dir, rpc_port, idx=i)
              for i in range(workers)]
    procs.append(_spawn("serving", data_dir, rpc_port))
    driver = MetaDriver(rpc_port)
    failures: list[str] = []
    try:
        deadline = time.monotonic() + 120
        while True:
            st = driver.call("cluster_state", deadline_s=120.0)
            live = [w for w in st["workers"] if w["alive"]]
            replicas = [r for r in st.get("serving", []) if r["alive"]]
            if len(live) >= workers and replicas:
                break
            if time.monotonic() > deadline:
                raise TimeoutError("cluster never fully registered")
            for p in procs:
                if p.poll() is not None:
                    raise RuntimeError(
                        f"a role died at startup (logs in {data_dir})")
            time.sleep(0.25)

        for sql in DDL:
            driver.call("execute_ddl", sql=sql)

        committed: list[int] = []
        for _ in range(rounds):
            round_deadline = time.monotonic() + 240
            while True:
                res = driver.call("tick", chunks_per_barrier=1)
                if res["committed"]:
                    committed.append(res["round"])
                    break
                if time.monotonic() > round_deadline:
                    raise TimeoutError("round never committed")
                time.sleep(0.2)
            # a serving read per round: once the replica's heartbeat
            # picks up the round ctx, sampled read spans join the tree
            driver.call("serve", sql=READ, deadline_s=180.0)
        # let serving heartbeats fetch the last round ctx + read once
        time.sleep(0.6)
        driver.call("serve", sql=READ, deadline_s=180.0)
        # drain the async uploaders' ckpt spans into the ring
        time.sleep(0.5)

        round_reports = {}
        serving_rounds = 0
        for rn in committed:
            tr = driver.call("cluster_trace", round=rn)
            names = {s["name"] for s in tr["spans"]}
            chk = tr["check"]
            if not chk["complete"]:
                failures.append(f"round {rn}: tree incomplete {chk}")
            missing = (META_SPANS | WORKER_SPANS) - names
            if missing:
                failures.append(
                    f"round {rn}: missing spans {sorted(missing)}")
            # the meta round span must COVER every worker seal span
            root = _span_window(tr["spans"], "round")
            seal = _span_window(tr["spans"], "seal")
            if root and seal:
                slack = 0.25
                if seal[0] < root[0] - slack or seal[1] > root[1] + slack:
                    failures.append(
                        f"round {rn}: seal window {seal} outside "
                        f"round window {root}")
            if "serving_read" in names:
                serving_rounds += 1
            round_reports[rn] = {"names": sorted(names),
                                 "check": chk}
        # uploader spans are async: require them in at least one round
        all_names = {n for r in round_reports.values()
                     for n in r["names"]}
        for want in ("ckpt_prepare", "ckpt_commit"):
            if want not in all_names:
                failures.append(f"no {want} span in any round")
        if serving_rounds == 0:
            failures.append("no sampled serving_read span joined "
                            "any round trace")

        # chrome export loads as trace_event JSON
        last = driver.call("cluster_trace", round=committed[-1])
        from risingwave_tpu.common.trace import to_chrome_trace
        ct = to_chrome_trace(last["spans"])
        if chrome:
            with open(chrome, "w") as f:
                json.dump(ct, f)
        if not ct["traceEvents"] or not any(
                e.get("ph") == "X" for e in ct["traceEvents"]):
            failures.append("chrome export has no complete events")

        # unified metrics plane: aggregated scrape + meta /metrics
        mtext = driver.call("cluster_metrics")["prometheus"]
        if 'barrier_phase_seconds_bucket{job="qcnt"' not in mtext:
            failures.append(
                "aggregated scrape lacks barrier_phase_seconds for "
                "the live job")
        if 'role="meta"' not in mtext or "worker=" not in mtext:
            failures.append("aggregated scrape lacks identity labels")
        import urllib.request
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{metrics_port}/metrics",
                    timeout=10) as resp:
                http_text = resp.read().decode()
            if "cluster_epoch" not in http_text:
                failures.append("/metrics endpoint missing meta gauges")
        except OSError as e:
            failures.append(f"/metrics endpoint unreachable: {e!r}")

        return {
            "rounds_committed": committed,
            "serving_read_rounds": serving_rounds,
            "round_reports": round_reports,
            "chrome_events": len(ct["traceEvents"]),
            "failures": failures,
            "data_dir": data_dir,
        }
    finally:
        driver.close()
        for p in procs:
            if p.poll() is None:
                p.kill()
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass


def run_overhead(iters: int = 6, chunks: int = 4) -> dict:
    """Gate 2: tracing on/off A/B on an in-process q1-style loop.
    Interleaved segments, medians — the contract is that DISABLED
    tracing costs nothing measurable on the chunk path."""
    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.common.trace import GLOBAL_TRACE
    from risingwave_tpu.sql.engine import Engine

    eng = Engine(RwConfig.from_dict(CONFIG))
    eng.execute(DDL[0])
    eng.execute(
        # q1-style stateless projection over the bid stream
        "CREATE MATERIALIZED VIEW q1 AS "
        "SELECT auction % 32 AS a, count(*) AS n FROM bid "
        "GROUP BY auction % 32"
    )
    eng.tick(barriers=2, chunks_per_barrier=chunks)  # warm/compile

    def segment() -> float:
        t0 = time.perf_counter()
        eng.tick(barriers=1, chunks_per_barrier=chunks)
        return time.perf_counter() - t0

    on: list[float] = []
    off: list[float] = []
    prev = GLOBAL_TRACE.sample_n
    try:
        for _ in range(iters):
            GLOBAL_TRACE.configure(sample_n=1)
            on.append(segment())
            GLOBAL_TRACE.configure(sample_n=0)
            off.append(segment())
    finally:
        GLOBAL_TRACE.configure(sample_n=prev)
    med_on = sorted(on)[len(on) // 2]
    med_off = sorted(off)[len(off) // 2]
    overhead = (med_on - med_off) / med_off if med_off > 0 else 0.0
    return {"median_on_s": round(med_on, 5),
            "median_off_s": round(med_off, 5),
            "overhead_frac": round(overhead, 4)}


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--rounds", type=int, default=6)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--chrome", default=None,
                   help="also write Chrome trace_event JSON here")
    p.add_argument("--overhead-iters", type=int, default=6)
    p.add_argument("--overhead-budget", type=float, default=0.02)
    p.add_argument("--skip-overhead", action="store_true")
    p.add_argument("--skip-cluster", action="store_true")
    p.add_argument("--assert", dest="check", action="store_true",
                   help="exit nonzero unless every committed round "
                        "assembles a complete cross-role span tree "
                        "and the A/B overhead is under budget")
    args = p.parse_args()

    summary: dict = {}
    ok = True
    if not args.skip_cluster:
        cl = run_cluster(rounds=args.rounds, workers=args.workers,
                         chrome=args.chrome)
        summary["cluster"] = {k: v for k, v in cl.items()
                              if k != "round_reports"}
        ok &= not cl["failures"]
    if not args.skip_overhead:
        ov = run_overhead(iters=args.overhead_iters)
        summary["overhead"] = ov
        ov["budget"] = args.overhead_budget
        ov["ok"] = ov["overhead_frac"] < args.overhead_budget
        ok &= ov["ok"]
    summary["ok"] = bool(ok)
    print(json.dumps(summary))
    if args.check:
        raise SystemExit(0 if ok else 1)


if __name__ == "__main__":
    main()
