"""TPC-H-as-MV .slt conformance: run the reference corpus, emit a report.

Consumes the REFERENCE's engine-agnostic sqllogictest corpus
(/root/reference/e2e_test/tpch/ table setup + inserts,
/root/reference/e2e_test/streaming/tpch/ view definitions + expected
results) against this engine, one query at a time, and rewrites the
TPCH section of CONFORMANCE.md.  Queries the planner or parser rejects
are SKIPPED (feature gaps, each with its reason); result mismatches
are FAILURES (correctness bugs).

Usage: JAX_PLATFORMS=cpu python scripts/conformance_tpch.py [ref_root]
       RWT_ONLY=q1,q6 filters (and then does NOT rewrite the report).
"""

from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import risingwave_tpu  # noqa: F401,E402
from risingwave_tpu.slt import SltError, run_slt  # noqa: E402
from risingwave_tpu.sql import Engine  # noqa: E402
from risingwave_tpu.sql.planner import PlannerConfig  # noqa: E402

from _report import replace_section  # noqa: E402

REF = sys.argv[1] if len(sys.argv) > 1 else "/root/reference"
SETUP_DIR = os.path.join(REF, "e2e_test/tpch")
QUERY_DIR = os.path.join(REF, "e2e_test/streaming/tpch")
OUT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "CONFORMANCE.md")

TABLES = ("supplier", "part", "partsupp", "customer", "orders",
          "lineitem", "nation", "region")


def make_engine() -> Engine:
    # The fast fused config: every query up to ~6 base tables passes
    # with it (chunked 512-row ingestion, pooled append-only join
    # sides).  The 8-9-table plans (q2/q8/q9) need the STAGED runtime
    # + dense sides (see DagJob.staged) but exceed the single-CPU-core
    # host budget either way — they run excluded here with the reason
    # recorded.
    return Engine(PlannerConfig(
        chunk_capacity=512,
        agg_table_size=1 << 13,
        agg_emit_capacity=1 << 12,
        join_table_size=1 << 13,
        join_bucket_cap=128,
        join_out_capacity=1 << 15,
        mv_table_size=1 << 13,
        mv_ring_size=1 << 15,
        topn_pool_size=1 << 12,
        topn_emit_capacity=1 << 11,
        minput_bucket_cap=128,
    ))


def run() -> dict:
    eng = make_engine()
    # no recovery in a conformance run: skip the per-commit in-memory
    # snapshot copy (a full extra state copy per barrier on deep plans)
    eng.execute(
        "ALTER SYSTEM SET snapshot_interval_checkpoints = 1000000"
    )
    run_slt(eng, os.path.join(SETUP_DIR, "create_tables.slt.part"),
            tick_between=0)
    for t in TABLES:
        run_slt(eng, os.path.join(SETUP_DIR, f"insert_{t}.slt.part"),
                tick_between=0)
    eng.tick(barriers=2)

    results: dict[str, tuple[str, str]] = {}
    names = sorted(
        (f[:-len(".slt.part")] for f in os.listdir(QUERY_DIR)
         if re.match(r"q\d+\.slt\.part$", f)),
        key=lambda s: int(s[1:]),
    )
    only = os.environ.get("RWT_ONLY")
    if only:
        names = [n for n in names if n in only.split(",")]
    excluded = os.environ.get("RWT_EXCLUDE", "")
    for name in excluded.split(","):
        if name in names:
            names.remove(name)
            results[name] = ("excluded", os.environ.get(
                "RWT_EXCLUDE_REASON", "excluded by RWT_EXCLUDE"))
    for name in names:
        print(f"... running {name}", flush=True)
        view_file = os.path.join(QUERY_DIR, "views", f"{name}.slt.part")
        query_file = os.path.join(QUERY_DIR, f"{name}.slt.part")
        before = {e.name for e in eng.catalog.list()}
        try:
            run_slt(eng, view_file, tick_between=0)
        except SltError as e:
            results[name] = ("skip", f"plan: {str(e.message)[:200]}")
            _drop_new(eng, before)
            continue
        except Exception as e:  # engine bug during CREATE
            results[name] = ("error", f"create: {e}"[:200])
            _drop_new(eng, before)
            continue
        try:
            eng.execute("FLUSH")
            eng.tick(barriers=2)
            run_slt(eng, query_file, tick_between=0)
            results[name] = ("pass", "")
        except SltError as e:
            results[name] = ("fail", str(e.message)[:6000])
        except Exception as e:
            results[name] = ("error", str(e)[:300])
        _drop_new(eng, before)
        st, detail = results[name]
        print(f"{name:6s} {st:5s} {detail[:120]}", flush=True)
    return results


def _drop_new(eng: Engine, before: set) -> None:
    new = [e.name for e in eng.catalog.list() if e.name not in before]
    for name in reversed(new):
        try:
            eng.execute(f"DROP MATERIALIZED VIEW {name}")
        except Exception:
            pass


def main() -> None:
    results = run()
    only = os.environ.get("RWT_ONLY")
    counts = {"pass": 0, "skip": 0, "fail": 0, "error": 0,
              "excluded": 0}
    for status, _ in results.values():
        counts[status] += 1
    lines = [
        "## TPC-H-as-MV conformance (reference .slt corpus)",
        "",
        "Source: `/root/reference/e2e_test/{tpch,streaming/tpch}` — the"
        " reference's own sqllogictest files run unmodified.",
        "",
        f"**{counts['pass']} passed, {counts['skip']} skipped "
        f"(unsupported feature), {counts['excluded']} excluded "
        f"(operator: exceeds the CPU-host run budget), "
        f"{counts['fail']} failed, "
        f"{counts['error']} errored** "
        f"out of {len(results)} queries.",
        "",
        "| query | status | detail |",
        "|---|---|---|",
    ]
    for name, (status, detail) in results.items():
        detail = detail.replace("|", "\\|").replace("\n", " ")[:300]
        lines.append(f"| {name} | {status} | {detail} |")
    lines.append("")
    if not only:
        replace_section(OUT, "tpch", "\n".join(lines))
        print(f"report written to {OUT}")
    for name, (status, detail) in results.items():
        print(f"{name:6s} {status:5s} {detail[:150]}")


if __name__ == "__main__":
    main()
