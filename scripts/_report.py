"""Marker-delimited section replacement for shared report files.

CONFORMANCE.md holds one section per conformance suite (nexmark,
tpch, ...) plus hand-maintained sections (known deviations); each
runner rewrites ONLY its own section so suites can run independently.
"""

from __future__ import annotations

import os


def replace_section(path: str, tag: str, content: str) -> None:
    begin = f"<!-- {tag}:begin -->"
    end = f"<!-- {tag}:end -->"
    block = f"{begin}\n{content.rstrip()}\n{end}"
    if os.path.exists(path):
        with open(path) as f:
            text = f.read()
    else:
        text = ""
    if begin in text and end in text:
        head, rest = text.split(begin, 1)
        _, tail = rest.split(end, 1)
        text = head + block + tail
    else:
        if text and not text.endswith("\n"):
            text += "\n"
        text += ("\n" if text else "") + block + "\n"
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
