"""Recurring accelerator probe: forensic record of chip availability.

Round-2 verdict demanded either device numbers or a blocker record.
This script attempts backend init with a hard timeout and appends one
JSON line per attempt to TPU_PROBE_LOG.jsonl (repo root): timestamp,
outcome, init seconds, and a sanity-matmul time when the chip is up.
Run as a loop (scripts/tpu_probe_loop.sh) or one-shot.
"""
import json
import os
import subprocess
import sys
import time

LOG = os.path.join(os.path.dirname(__file__), "..", "TPU_PROBE_LOG.jsonl")

CHILD = r'''
import json, time
t0 = time.time()
import jax
devs = jax.devices()
rec = {"devices": [str(d) for d in devs], "platform": devs[0].platform,
       "init_seconds": round(time.time() - t0, 1)}
if devs[0].platform == "cpu":
    # sitecustomize pins jax_platforms to "axon,cpu": a fast axon
    # failure silently falls through to CPU — that is NOT a chip
    print("PROBE_CPU_FALLBACK " + json.dumps(rec))
    raise SystemExit(0)
import jax.numpy as jnp
x = jnp.ones((4096, 4096), dtype=jnp.bfloat16)
t1 = time.time()
y = (x @ x).block_until_ready()
rec["matmul_4k_ms_incl_compile"] = round((time.time() - t1) * 1e3, 1)
t2 = time.time()
for _ in range(10):
    y = (y @ x)
y.block_until_ready()
rec["matmul_4k_ms_steady"] = round((time.time() - t2) * 1e2, 2)
print("PROBE_OK " + json.dumps(rec))
'''

def probe(timeout_s: float = 600.0) -> dict:
    rec = {"t": time.strftime("%Y-%m-%dT%H:%M:%S"), "timeout_s": timeout_s}
    t0 = time.time()
    try:
        out = subprocess.run([sys.executable, "-c", CHILD],
                             capture_output=True, text=True,
                             timeout=timeout_s)
        for line in out.stdout.splitlines():
            if line.startswith("PROBE_OK "):
                rec.update(json.loads(line[len("PROBE_OK "):]))
                rec["ok"] = True
                break
            if line.startswith("PROBE_CPU_FALLBACK "):
                rec.update(json.loads(line.split(" ", 1)[1]))
                rec["ok"] = False
                rec["error"] = ("backend init fell back to CPU "
                                "(accelerator claim failed fast)")
                break
        else:
            rec["ok"] = False
            rec["error"] = (out.stderr or out.stdout)[-400:]
    except subprocess.TimeoutExpired:
        rec["ok"] = False
        rec["error"] = f"backend init hung > {timeout_s:.0f}s (killed)"
    rec["elapsed_s"] = round(time.time() - t0, 1)
    return rec

if __name__ == "__main__":
    timeout_s = float(sys.argv[1]) if len(sys.argv) > 1 else 600.0
    rec = probe(timeout_s)
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))
