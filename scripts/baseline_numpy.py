"""Independent CPU baseline: the bench queries in plain numpy.

Round-3 verdict ask #2: `vs_baseline` must not be this framework
measuring itself.  The reference itself cannot run here — no
rustc/cargo in the image and zero network egress (BASELINE.md records
the attempt) — so this provides an INDEPENDENT denominator: each bench
query implemented directly in single-threaded numpy (dict + ufunc
streaming, the idiomatic "hand-rolled Python stream processor"),
consuming the IDENTICAL event stream as bench.py.

Event generation happens OUTSIDE the timed window (bench.py generates
on device inside the step; this baseline gets generation for free,
biasing in the BASELINE's favor — the honest direction).

Usage: JAX_PLATFORMS=cpu python scripts/baseline_numpy.py [q1|q5|q7|q8|all]
Prints one `NUMPY <query> <rows/s>` line per query.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import risingwave_tpu  # noqa: F401,E402
import numpy as np  # noqa: E402

CHUNK = 8192
CHUNKS = 40 * 8  # bench.py: 32 measured + warmup barriers x 8 chunks

S = 1_000_000  # us per second


def gen_bids(n_chunks: int):
    """Host bid stream via the device generator (outside timing)."""
    import jax
    from risingwave_tpu.connector.nexmark import (
        NexmarkConfig, NexmarkGenerator,
    )
    gen = NexmarkGenerator(NexmarkConfig(inter_event_us=1))
    out = []
    for i in range(n_chunks):
        c = gen.gen_bids(jax.numpy.int64(i * CHUNK), CHUNK)
        _, cols, _ = c.to_host()
        out.append(tuple(np.asarray(x) for x in cols))
    return out


def gen_table(table: str, n_chunks: int):
    import jax
    from risingwave_tpu.connector.nexmark import (
        NexmarkConfig, NexmarkGenerator,
    )
    gen = NexmarkGenerator(NexmarkConfig(inter_event_us=1))
    fn = {"person": gen.gen_persons, "auction": gen.gen_auctions}[table]
    out = []
    for i in range(n_chunks):
        c = fn(jax.numpy.int64(i * CHUNK), CHUNK)
        _, cols, _ = c.to_host()
        out.append(tuple(np.asarray(x) for x in cols))
    return out


def run_q1(chunks) -> float:
    outs = []
    t0 = time.perf_counter()
    for cols in chunks:
        auction, bidder, price, _c, _u, ts = cols[:6]
        outs.append((auction, bidder, 0.908 * price, ts))
    dt = time.perf_counter() - t0
    return len(chunks) * CHUNK / dt


def run_q5(chunks) -> float:
    # HOP 2s slide / 10s size: 5 windows per event
    counts: dict = {}
    t0 = time.perf_counter()
    for cols in chunks:
        auction, ts = cols[0], cols[5]
        base = (ts // (2 * S)) * (2 * S)
        for k in range(5):
            ws = base - k * 2 * S
            keys = np.stack([auction, ws], axis=1)
            uniq, cnt = np.unique(keys, axis=0, return_counts=True)
            for (a, w), n in zip(uniq, cnt):
                counts[(int(a), int(w))] = counts.get(
                    (int(a), int(w)), 0) + int(n)
    dt = time.perf_counter() - t0
    assert counts
    return len(chunks) * CHUNK / dt


def run_q7(chunks) -> float:
    mx: dict = {}
    cnt: dict = {}
    t0 = time.perf_counter()
    for cols in chunks:
        price, ts = cols[2], cols[5]
        win = (ts // (10 * S)) * (10 * S)
        uniq, inv = np.unique(win, return_inverse=True)
        m = np.full(uniq.shape[0], -1, np.int64)
        np.maximum.at(m, inv, price)
        c = np.bincount(inv, minlength=uniq.shape[0])
        for w, mval, n in zip(uniq, m, c):
            w = int(w)
            mx[w] = max(mx.get(w, -1), int(mval))
            cnt[w] = cnt.get(w, 0) + int(n)
    dt = time.perf_counter() - t0
    assert mx
    return len(chunks) * CHUNK / dt


def run_q8(pchunks, achunks) -> float:
    # TUMBLE 1s join persons x auctions ON p.id = a.seller AND same window
    out_rows = 0
    persons: dict = {}   # (window, id) -> name idx count
    auctions: dict = {}  # (window, seller) -> count
    t0 = time.perf_counter()
    for pcols, acols in zip(pchunks, achunks):
        # full generator schemas: person ts at 6; auction seller at 7,
        # ts at 5 (connector/nexmark.py PERSON_SCHEMA/AUCTION_SCHEMA)
        pid, pts = pcols[0], pcols[6]
        pw = (pts // S) * S
        aid_seller, ats = acols[7], acols[5]
        aw = (ats // S) * S
        # build person side
        pk = np.stack([pw, pid], axis=1)
        uniq, cnt = np.unique(pk, axis=0, return_counts=True)
        for (w, i), n in zip(uniq, cnt):
            persons[(int(w), int(i))] = persons.get(
                (int(w), int(i)), 0) + int(n)
        # probe with auctions (and symmetric count for fairness)
        ak = np.stack([aw, aid_seller], axis=1)
        auniq, acnt = np.unique(ak, axis=0, return_counts=True)
        for (w, s), n in zip(auniq, acnt):
            auctions[(int(w), int(s))] = auctions.get(
                (int(w), int(s)), 0) + int(n)
            out_rows += persons.get((int(w), int(s)), 0) * int(n)
    dt = time.perf_counter() - t0
    assert out_rows > 0
    return 2 * len(pchunks) * CHUNK / dt


def main() -> None:
    which = sys.argv[1] if len(sys.argv) > 1 else \
        os.environ.get("Q", "all")
    results = {}
    if which in ("q1", "q5", "q7", "all"):
        bids = gen_bids(CHUNKS)
        if which in ("q1", "all"):
            results["q1"] = run_q1(bids)
        if which in ("q5", "all"):
            results["q5"] = run_q5(bids)
        if which in ("q7", "all"):
            results["q7"] = run_q7(bids)
    if which in ("q8", "all"):
        p = gen_table("person", CHUNKS)
        a = gen_table("auction", CHUNKS)
        results["q8"] = run_q8(p, a)
    for q, v in results.items():
        print(f"NUMPY {q} {v:.1f}")


if __name__ == "__main__":
    main()
