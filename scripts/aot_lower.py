"""AOT device evidence: lower the bench step programs to StableHLO.

Round-3 verdict ask #1: with the TPU tunnel dead for three rounds,
produce DEVICELESS evidence that the programs are device-ready —
AOT-lowered StableHLO artifacts committed to the repo plus an audit
for host round-trips and dynamic shapes, and (when the local runtime
allows it) a deviceless TPU compile via jax.experimental.topologies.

Artifacts land in artifacts/aot/:
  <q>_step.stablehlo.txt.gz      — the fused source→executors step
  <q>_barrier.stablehlo.txt.gz   — the one-dispatch barrier crossing
  q5_sharded8_step.stablehlo.txt.gz — the 8-shard shard_map program
  AOT_AUDIT.md                   — audit summary (regenerated)

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       python scripts/aot_lower.py
"""

from __future__ import annotations

import gzip
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import risingwave_tpu  # noqa: F401,E402  (platform config)
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from risingwave_tpu.sql import Engine  # noqa: E402
from risingwave_tpu.sql.planner import PlannerConfig  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT_DIR = os.path.join(ROOT, "artifacts", "aot")

SOURCES = """
CREATE SOURCE bid (
    auction BIGINT, bidder BIGINT, price BIGINT,
    channel VARCHAR, url VARCHAR, date_time TIMESTAMP
) WITH (connector = 'nexmark', nexmark.table = 'bid',
        nexmark.event.rate = '1000000');
CREATE SOURCE person (
    id BIGINT, name VARCHAR, date_time TIMESTAMP,
    WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
) WITH (connector = 'nexmark', nexmark.table = 'person',
        nexmark.event.rate = '1000000');
CREATE SOURCE auction (
    id BIGINT, seller BIGINT, reserve BIGINT, expires TIMESTAMP,
    date_time TIMESTAMP,
    WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
) WITH (connector = 'nexmark', nexmark.table = 'auction',
        nexmark.event.rate = '1000000');
"""

QUERIES = {
    "q1": """
        CREATE MATERIALIZED VIEW bench_mv AS
        SELECT auction, bidder, 0.908 * price AS price, date_time
        FROM bid;
    """,
    "q5": """
        CREATE MATERIALIZED VIEW bench_mv AS
        SELECT auction, window_start, count(*) AS bids
        FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
        GROUP BY auction, window_start;
    """,
    "q7": """
        CREATE MATERIALIZED VIEW bench_mv AS
        SELECT window_start, max(price) AS max_price, count(*) AS bids
        FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND)
        GROUP BY window_start;
    """,
    "q8": """
        CREATE MATERIALIZED VIEW bench_mv AS
        SELECT p.id AS id, p.name AS name, a.reserve AS reserve
        FROM TUMBLE(person, date_time, INTERVAL '1' SECOND) p
        JOIN TUMBLE(auction, date_time, INTERVAL '1' SECOND) a
        ON p.id = a.seller AND p.window_start = a.window_start;
    """,
}

#: bench-shape config, scaled down 16x in table sizes to keep the
#: committed artifacts reviewable (the PROGRAM structure — fusion,
#: scatter shapes, control flow — is identical; only constants differ)
CONFIG = dict(
    chunk_capacity=8192,
    agg_table_size=1 << 14,
    agg_emit_capacity=4096,
    join_left_table_size=1 << 18,
    join_right_table_size=1 << 14,
    join_pool_size=1 << 18,
    join_out_capacity=1 << 15,
    mv_table_size=1 << 14,
    mv_ring_size=1 << 17,
    topn_pool_size=1 << 14,
)


def build_engine() -> Engine:
    eng = Engine(PlannerConfig(**CONFIG))
    eng.execute(SOURCES)
    return eng


def tpu_compile(jitted, args, name: str) -> dict:
    """Deviceless AOT compile for TPU (the local libtpu supports
    jax.experimental.topologies): THE device-readiness proof — XLA:TPU
    accepts and schedules the program, and memory_analysis() reports
    its HBM footprint, all without a chip."""
    import time
    t0 = time.time()
    try:
        lowered = jitted.trace(*args).lower(lowering_platforms=("tpu",))
        compiled = lowered.compile()
        mem = compiled.memory_analysis()
        out = {"name": name, "ok": True,
               "seconds": round(time.time() - t0, 1)}
        for attr in ("argument_size_in_bytes", "output_size_in_bytes",
                     "temp_size_in_bytes", "generated_code_size_in_bytes"):
            v = getattr(mem, attr, None)
            if v is not None:
                out[attr] = int(v)
        return out
    except Exception as e:  # noqa: BLE001 — forensic record
        return {"name": name, "ok": False,
                "seconds": round(time.time() - t0, 1),
                "error": f"{type(e).__name__}: {str(e)[:240]}"}


def audit_text(name: str, text: str) -> dict:
    """Grep-level HLO audit: device-readiness red flags."""
    custom_calls = re.findall(r'stablehlo\.custom_call\s*@?"?([\w.]+)', text)
    callbacks = [c for c in custom_calls
                 if "callback" in c or "py_" in c.lower()]
    dyn = len(re.findall(r"tensor<\?", text))
    infeed = len(re.findall(r"infeed|outfeed", text))
    collectives = len(re.findall(
        r"all_to_all|all_reduce|all_gather|collective_permute|"
        r"reduce_scatter", text))
    return {
        "name": name,
        "bytes": len(text),
        "custom_calls": sorted(set(custom_calls)),
        "host_callbacks": callbacks,
        "dynamic_shapes": dyn,
        "infeed_outfeed": infeed,
        "collectives": collectives,
        "while_loops": len(re.findall(r"stablehlo\.while", text)),
    }


def save(name: str, text: str) -> None:
    os.makedirs(OUT_DIR, exist_ok=True)
    with gzip.open(os.path.join(OUT_DIR, f"{name}.stablehlo.txt.gz"),
                   "wt") as f:
        f.write(text)


def lower_linear(query: str, compiles: list) -> list[dict]:
    eng = build_engine()
    eng.execute(QUERIES[query])
    job = eng.jobs[0]
    audits = []
    if getattr(job, "_fused", None) is not None:
        step = jax.jit(
            lambda s, k: job.fragment._step_impl(
                s, job.source.impl(k, job.source.cap))
        )
        lowered = step.lower(job.states, jnp.int64(0))
        text = lowered.as_text()
        save(f"{query}_step", text)
        audits.append(audit_text(f"{query}_step", text))
        compiles.append(tpu_compile(
            step, (job.states, jnp.int64(0)), f"{query}_step"
        ))
        barrier = jax.jit(job.fragment._barrier_impl)
        btext = barrier.lower(job.states, jnp.int64(0)).as_text()
        save(f"{query}_barrier", btext)
        audits.append(audit_text(f"{query}_barrier", btext))
        compiles.append(tpu_compile(
            barrier, (job.states, jnp.int64(0)), f"{query}_barrier"
        ))
    else:
        # DAG job (q8): lower its per-source step + barrier programs
        for src in job.sources:
            if src not in job._step_programs:
                job._step_programs[src] = job._make_step(src)
            prog, fused = job._step_programs[src]
            if not fused:
                continue
            lowered = prog.lower(job.states, jnp.int64(0))
            text = lowered.as_text()
            save(f"{query}_step_{src}", text)
            audits.append(audit_text(f"{query}_step_{src}", text))
            compiles.append(tpu_compile(
                prog, (job.states, jnp.int64(0)), f"{query}_step_{src}"
            ))
        if job._barrier_prog is None:
            job._barrier_prog = job._make_barrier_prog()
        blowered = job._barrier_prog.lower(job.states, jnp.int64(0))
        btext = blowered.as_text()
        save(f"{query}_barrier", btext)
        audits.append(audit_text(f"{query}_barrier", btext))
        compiles.append(tpu_compile(
            job._barrier_prog, (job.states, jnp.int64(0)),
            f"{query}_barrier"
        ))
    return audits


def lower_sharded(query: str = "q5") -> list[dict]:
    if len(jax.devices()) < 8:
        return [{"name": f"{query}_sharded8", "error":
                 "needs 8 virtual devices (xla_force_host_platform_"
                 "device_count=8)"}]
    eng = build_engine()
    eng.execute("SET streaming_parallelism = 8")
    eng.execute(QUERIES[query])
    job = eng.jobs[0]
    sharded = getattr(job, "sharded", None)
    if sharded is None:
        return [{"name": f"{query}_sharded8",
                 "error": f"plan did not shard ({type(job).__name__})"}]
    k0 = jnp.zeros((sharded.n_shards, 1), jnp.int64)
    lowered = sharded._step.lower(job.states, k0)
    text = lowered.as_text()
    save(f"{query}_sharded8_step", text)
    return [audit_text(f"{query}_sharded8_step", text)]


def try_tpu_topology_compile() -> str:
    """Deviceless TPU compile (needs a local libtpu); record outcome."""
    try:
        from jax.experimental import topologies
        topo = topologies.get_topology_desc(
            platform="tpu", topology_name="v5e:2x2"
        )
        return f"topology acquired: {topo}"
    except Exception as e:  # noqa: BLE001 — forensic record
        return f"unavailable: {type(e).__name__}: {str(e)[:300]}"


def main() -> None:
    audits: list = []
    compiles: list = []
    for q in ("q1", "q5", "q7", "q8"):
        print(f"lowering {q} ...", flush=True)
        audits.extend(lower_linear(q, compiles))
    print("lowering sharded q5 ...", flush=True)
    audits.extend(lower_sharded("q5"))
    topo = try_tpu_topology_compile()

    lines = [
        "# AOT device-readiness audit",
        "",
        "StableHLO artifacts in `artifacts/aot/` — the bench step and",
        "barrier programs AOT-lowered (deviceless) and audited for",
        "device-readiness.  Red flags would be host callbacks",
        "(`*_callback` custom calls), infeed/outfeed, or dynamic",
        "(`tensor<?`) shapes — any of those would stall a TPU.",
        "",
        f"Deviceless TPU topology: {topo}",
        "",
        "## StableHLO audit",
        "",
        "| program | KiB (text) | host callbacks | dyn shapes | "
        "infeed | collectives | while loops | custom calls |",
        "|---|---|---|---|---|---|---|",
    ]
    for a in audits:
        if "error" in a:
            lines.append(f"| {a['name']} | — | {a['error']} | | | | | |")
            continue
        lines.append(
            f"| {a['name']} | {a['bytes'] // 1024} | "
            f"{len(a['host_callbacks'])} | {a['dynamic_shapes']} | "
            f"{a['infeed_outfeed']} | {a['collectives']} | "
            f"{a['while_loops']} | "
            f"{', '.join(a['custom_calls'][:6]) or '—'} |"
        )
    lines += [
        "",
        "## Deviceless XLA:TPU compiles (v5e, no chip attached)",
        "",
        "Each bench program compiled end-to-end by XLA:TPU via",
        "`jax.experimental.topologies` — the strongest no-chip proof",
        "that the programs run on the target: the TPU compiler",
        "accepted, scheduled, and sized them.",
        "",
        "| program | compiled | seconds | args MiB | temp (HBM) MiB | "
        "code MiB |",
        "|---|---|---|---|---|---|",
    ]
    MB = 1024 * 1024
    for c in compiles:
        if not c.get("ok"):
            lines.append(
                f"| {c['name']} | FAILED | {c['seconds']} | "
                f"{c.get('error', '')} | | |"
            )
            continue
        lines.append(
            f"| {c['name']} | yes | {c['seconds']} | "
            f"{c.get('argument_size_in_bytes', 0) // MB} | "
            f"{c.get('temp_size_in_bytes', 0) // MB} | "
            f"{c.get('generated_code_size_in_bytes', 0) // MB} |"
        )
    lines.append("")
    os.makedirs(OUT_DIR, exist_ok=True)
    with open(os.path.join(OUT_DIR, "AOT_AUDIT.md"), "w") as f:
        f.write("\n".join(lines))
    print("\n".join(lines))


if __name__ == "__main__":
    main()
