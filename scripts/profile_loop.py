"""Time the actual engine barrier loop for q7 (async-path version)."""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import risingwave_tpu  # noqa: F401
import jax

from risingwave_tpu.sql import Engine
from risingwave_tpu.sql.planner import PlannerConfig

CAP = 8192


def main():
    eng = Engine(PlannerConfig(
        chunk_capacity=CAP, agg_table_size=1 << 18, agg_emit_capacity=4096,
        mv_table_size=1 << 18, mv_ring_size=1 << 21))
    eng.execute("""
    CREATE SOURCE bid (
        auction BIGINT, bidder BIGINT, price BIGINT,
        channel VARCHAR, url VARCHAR, date_time TIMESTAMP
    ) WITH (connector = 'nexmark', nexmark.table = 'bid',
            nexmark.event.rate = '1000000');
    """)
    eng.execute("""
    CREATE MATERIALIZED VIEW bench_mv AS
    SELECT window_start, max(price) AS max_price, count(*) AS bids
    FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND)
    GROUP BY window_start;
    """)
    eng.execute("ALTER SYSTEM SET maintenance_interval_checkpoints = 8")
    eng.execute("ALTER SYSTEM SET snapshot_interval_checkpoints = 8")
    job = eng.jobs[0]
    eng.tick(barriers=9, chunks_per_barrier=8)  # warm/compile incl. maint
    jax.block_until_ready(job.states)

    N = 16
    t0 = time.perf_counter()
    tc = 0.0
    tb = 0.0
    for _ in range(N):
        t1 = time.perf_counter()
        for _ in range(8):
            job.run_chunk()
        tc += time.perf_counter() - t1
        t1 = time.perf_counter()
        job.inject_barrier()
        tb += time.perf_counter() - t1
    jax.block_until_ready(job.states)
    total = time.perf_counter() - t0
    print(f"total {total*1e3:.1f} ms for {N} barriers "
          f"({CAP*8*N/total/1e6:.3f} Mrows/s)")
    print(f"  submit chunks  {tc*1e3:8.1f} ms")
    print(f"  submit barrier {tb*1e3:8.1f} ms")
    print(f"  device wait    {(total-tc-tb)*1e3:8.1f} ms")


if __name__ == "__main__":
    main()
