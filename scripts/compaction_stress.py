"""Compaction stress: sustained ingest + concurrent serving reads.

The acceptance harness for the storage-service split (ISSUE 1): with
the background ``CompactorService`` running,

- the ingest path performs ZERO merge I/O (``write_path_merges == 0``
  — compaction happens only in the service),
- the write-stall contract keeps the observed L0 run count at or
  below the stall threshold,
- concurrent serving reads through pinned versions see a consistent
  view with zero errors while the compactor rewrites levels and
  vacuum deletes their inputs underneath them,
- after a final vacuum the object store holds exactly the SSTs
  referenced by live versions.

Run standalone (prints one JSON summary line)::

    python scripts/compaction_stress.py --seconds 20

or the short ``slow``-marked pytest wrapper
(tests/test_hummock.py::test_compaction_stress_short).
"""

from __future__ import annotations

import argparse
import json
import struct
import sys
import threading
import time

sys.path.insert(0, ".")  # repo root

from risingwave_tpu.common.metrics import MetricsRegistry  # noqa: E402
from risingwave_tpu.storage.hummock import (  # noqa: E402
    CompactorService,
    HummockStorage,
    InMemObjectStore,
)
from risingwave_tpu.storage.hummock.store import SST_PREFIX  # noqa: E402


def _k(i: int) -> bytes:
    return struct.pack(">Q", i)


def run(seconds: float = 20.0, batch_rows: int = 256,
        key_space: int = 50_000, l0_trigger: int = 4,
        stall_l0: int = 12, vacuum_every_s: float = 0.5) -> dict:
    metrics = MetricsRegistry()
    storage = HummockStorage(
        InMemObjectStore(), metrics=metrics, l0_trigger=l0_trigger,
        base_bytes=1 << 16, ratio=4, stall_l0=stall_l0,
    )
    svc = CompactorService(storage, poll_interval_s=0.001).start()

    stop = threading.Event()
    state = {
        "read_errors": 0, "reads": 0, "scans": 0,
        "max_l0_observed": 0, "batches": 0, "stall_s": 0.0,
        "vacuum_deleted": 0,
    }
    #: committed model, guarded: readers verify against it
    model: dict[bytes, bytes] = {}
    model_lock = threading.Lock()

    def reader_loop():
        # race-free serving invariant: everything read under ONE pin
        # is immutable — scans repeat identically and point gets agree
        # with the scan, no matter what the compactor/vacuum/writer do
        # concurrently.  Any exception is a read error too.
        while not stop.is_set():
            try:
                with storage.pin() as pv:
                    a = list(pv.scan(_k(0), _k(512)))
                    for k, v in a[:32]:
                        state["reads"] += 1
                        if pv.get(k) != v:
                            state["read_errors"] += 1
                    if list(pv.scan(_k(0), _k(512))) != a:
                        state["read_errors"] += 1
                    state["scans"] += 1
            except Exception:
                state["read_errors"] += 1

    def vacuum_loop():
        while not stop.is_set():
            try:
                state["vacuum_deleted"] += storage.vacuum()
            except Exception:
                state["read_errors"] += 1
            stop.wait(vacuum_every_s)

    readers = [threading.Thread(target=reader_loop, daemon=True)
               for _ in range(2)]
    vac = threading.Thread(target=vacuum_loop, daemon=True)
    for t in readers:
        t.start()
    vac.start()

    deadline = time.monotonic() + seconds
    step = 0
    while time.monotonic() < deadline:
        step += 1
        base = (step * batch_rows) % key_space
        pairs = [(_k((base + j) % key_space),
                  f"s{step}".encode()) for j in range(batch_rows)]
        storage.write_batch(pairs, epoch=step)
        with model_lock:
            model.update(pairs)
        if step % 13 == 0:
            dels = [_k((base + j) % key_space)
                    for j in range(0, batch_rows, 7)]
            storage.delete_batch(dels, epoch=step)
            with model_lock:
                for d in dels:
                    model.pop(d, None)
        # the write-stall contract: ingest yields to the compactor
        state["stall_s"] += storage.wait_below_stall(timeout=10.0)
        state["batches"] = step
        state["max_l0_observed"] = max(state["max_l0_observed"],
                                       storage.l0_depth())

    stop.set()
    for t in readers:
        t.join(timeout=5)
    vac.join(timeout=5)
    svc.stop()
    svc.drain()

    # final verification: full scan equals the committed model
    got = dict(storage.scan())
    want = dict(sorted(model.items()))
    mismatches = sum(1 for k in want if got.get(k) != want[k])
    mismatches += sum(1 for k in got if k not in want)
    state["read_errors"] += mismatches
    storage.vacuum()
    live = set(storage.store.list(SST_PREFIX))
    orphans = live - storage.versions.referenced_keys()

    summary = {
        **state,
        "seconds": seconds,
        "stall_l0": stall_l0,
        "verified_rows": len(want),
        "final_mismatches": mismatches,
        "orphan_objects_after_vacuum": len(orphans),
        "compactor_tasks": svc.tasks_run,
        "compactor_errors": svc.errors,
        "write_path_merges": storage.write_path_merges,
        "final_l0": storage.l0_depth(),
        "stalled_final": storage.stalled(),
        "storage": storage.stats(),
    }
    return summary


def _mc(v: int) -> bytes:
    """int64 memcomparable encoding (sign-flip offset binary) for the
    non-negative seqs this phase uses."""
    return struct.pack(">Q", v ^ (1 << 63))


def run_ttl(rows: int = 3000, ttl: int = 800, batch: int = 500,
            l0_trigger: int = 2) -> dict:
    """The pushdown-plane TTL phase: a policy-managed table written
    in epoch batches, the horizon advancing with the max observed seq
    exactly as the engine derives it at export.  Floors:

    - the compaction filter provably drops rows
      (``pushdown_rows_elided > 0`` — never the write path);
    - ZERO resurrections: no key below the final horizon survives any
      number of further compactions;
    - unexpired reads are byte-identical to a policy-free replay of
      the same writes (expiry elides, never corrupts).
    """
    from risingwave_tpu.storage.pushdown import (
        ExpiryPolicy,
        table_prefix,
    )

    pfx = table_prefix("tt")

    def key(seq: int) -> bytes:
        return pfx + _mc(seq)

    def ingest(storage: HummockStorage, with_policy: bool) -> None:
        epoch = 0
        for lo in range(0, rows, batch):
            epoch += 1
            pairs = [(key(s), f"v{s}@{epoch}".encode())
                     for s in range(lo, min(lo + batch, rows))]
            # overwrite a slice of the previous batch so compaction
            # really merges generations, and tombstone a few keys
            # below the coming horizon (whole dead ranges elide)
            if lo:
                pairs += [(key(s), f"v{s}@{epoch}r".encode())
                          for s in range(lo - 32, lo)]
            storage.write_batch(pairs, epoch=epoch)
            if lo:
                storage.delete_batch(
                    [key(s) for s in range(lo - 64, lo - 48)],
                    epoch=epoch,
                )
            if with_policy:
                horizon = max(0, min(lo + batch, rows) - 1 - ttl)
                pol = ExpiryPolicy(
                    table="tt", prefix=pfx,
                    expire_below=pfx + _mc(horizon),
                    horizon=horizon, ttl=ttl, column="seq",
                    epoch=epoch,
                )
                storage.set_policy("tt", pol.to_doc())

    def mk() -> HummockStorage:
        return HummockStorage(
            InMemObjectStore(), metrics=MetricsRegistry(),
            l0_trigger=l0_trigger, base_bytes=1 << 14, ratio=4,
            stall_l0=64,
        )

    managed, plain = mk(), mk()
    ingest(managed, with_policy=True)
    ingest(plain, with_policy=False)
    for st in (managed, plain):
        while st.compact_once():
            pass
    horizon = managed.policy_set().get("tt").horizon

    got = dict(managed.scan())
    resurrected = sum(1 for k in got if pfx <= k < pfx + _mc(horizon))
    # compaction is idempotent under the policy: more passes, still
    # nothing below the horizon
    managed.write_batch([(key(rows + 1), b"tick")], epoch=99)
    while managed.compact_once():
        pass
    got2 = dict(managed.scan())
    resurrected += sum(1 for k in got2
                       if pfx <= k < pfx + _mc(horizon))

    replay = dict(plain.scan())
    unexpired_want = {k: v for k, v in replay.items()
                      if not (pfx <= k < pfx + _mc(horizon))}
    unexpired_got = {k: v for k, v in got.items() if k in replay}
    identical = unexpired_got == unexpired_want

    return {
        "rows": rows,
        "ttl": ttl,
        "horizon": horizon,
        "ttl_rows_elided": managed.pushdown_rows_elided,
        "ttl_blocks_skipped": managed.pushdown_blocks_skipped,
        "ttl_ssts_elided": managed.pushdown_ssts_elided,
        "resurrected": resurrected,
        "unexpired_identical": identical,
        "surviving_rows": len(got2),
    }


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seconds", type=float, default=20.0)
    p.add_argument("--batch-rows", type=int, default=256)
    p.add_argument("--key-space", type=int, default=50_000)
    p.add_argument("--l0-trigger", type=int, default=4)
    p.add_argument("--stall-l0", type=int, default=12)
    p.add_argument("--assert", dest="do_assert", action="store_true")
    args = p.parse_args()
    summary = run(seconds=args.seconds, batch_rows=args.batch_rows,
                  key_space=args.key_space, l0_trigger=args.l0_trigger,
                  stall_l0=args.stall_l0)
    summary["ttl"] = run_ttl()
    print(json.dumps(summary))
    ok = (summary["read_errors"] == 0
          and summary["max_l0_observed"] <= summary["stall_l0"]
          and summary["write_path_merges"] == 0
          and summary["orphan_objects_after_vacuum"] == 0)
    ttl = summary["ttl"]
    ttl_ok = (ttl["ttl_rows_elided"] > 0
              and ttl["resurrected"] == 0
              and ttl["unexpired_identical"])
    if args.do_assert and not ttl_ok:
        print(f"TTL floors FAILED: {ttl}", file=sys.stderr)
    raise SystemExit(0 if ok and ttl_ok else 1)


if __name__ == "__main__":
    main()
