"""Decompose q7 step time on the current backend.

Measures, per jitted call: dispatch floor (trivial kernel), source
generation, hop expansion, full q7 step (gen+hop+agg), and flush.
Run with JAX_PLATFORMS=cpu for the CPU comparison.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import risingwave_tpu  # noqa: F401
import jax
import jax.numpy as jnp

from risingwave_tpu.sql import Engine
from risingwave_tpu.sql.planner import PlannerConfig

CAP = 8192


def timeit(name, fn, n=30):
    fn()  # compile/warm
    jax.block_until_ready(fn())
    t0 = time.perf_counter()
    out = None
    for _ in range(n):
        out = fn()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{name:34s} {dt*1e3:9.3f} ms/call  {CAP/dt/1e6:8.2f} Mrows/s")
    return dt


def main():
    print("backend:", jax.default_backend())

    # dispatch floor: how much does one tiny jitted call cost?
    x = jnp.zeros((8,), jnp.int32)
    tiny = jax.jit(lambda v: v + 1)
    timeit("dispatch floor (v+1)", lambda: tiny(x), n=100)

    eng = Engine(PlannerConfig(
        chunk_capacity=CAP, agg_table_size=1 << 18, agg_emit_capacity=4096,
        mv_table_size=1 << 18, mv_ring_size=1 << 21))
    eng.execute("""
    CREATE SOURCE bid (
        auction BIGINT, bidder BIGINT, price BIGINT,
        channel VARCHAR, url VARCHAR, date_time TIMESTAMP
    ) WITH (connector = 'nexmark', nexmark.table = 'bid',
            nexmark.event.rate = '1000000');
    """)
    eng.execute("""
    CREATE MATERIALIZED VIEW bench_mv AS
    SELECT window_start, max(price) AS max_price, count(*) AS bids
    FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND)
    GROUP BY window_start;
    """)
    job = eng.jobs[0]
    src = job.source
    frag = job.fragment

    # source generation alone
    gen = jax.jit(lambda k0: src.impl(k0, src.cap))
    timeit("source gen (bid chunk)", lambda: gen(jnp.int64(12345)))

    # per-executor step decomposition: run the chain up to executor i
    chunk0 = gen(jnp.int64(12345))
    states = frag.init_states()
    names = [type(e).__name__ for e in frag.executors]
    print("executors:", names)

    for upto in range(1, len(frag.executors) + 1):
        sub = frag.executors[:upto]

        def partial_step(sts, ch, sub=sub):
            sts = list(sts)
            out = ch
            for i, ex in enumerate(sub):
                if out is None:
                    break
                sts[i], out = ex.apply(sts[i], out)
            return tuple(sts), out

        f = jax.jit(partial_step)
        st = frag.init_states()
        timeit(f"step thru {names[upto-1]:20s}", lambda: f(st, chunk0))

    # full fused step (gen + all executors), as the job runs it
    fused = job._fused
    st = frag.init_states()

    def run_fused():
        nonlocal st
        st, _ = fused(st, jnp.int64(src.next_base()))
        return st
    # note: donation means st is consumed; rebuild each call is wrong —
    # instead chain (realistic: state carries forward)
    timeit("full fused step (donated)", run_fused)

    # flush
    st2 = frag.init_states()
    fl = jax.jit(frag._flush_impl if hasattr(frag, "_flush_impl")
                 else lambda s, e: frag.flush(s, e))
    try:
        timeit("flush", lambda: fl(st2, jnp.int64(1)))
    except Exception as e:
        print("flush timing skipped:", e)


if __name__ == "__main__":
    main()
