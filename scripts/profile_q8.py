"""Attribute q8's cost: stage-by-stage timings of the windowed join.

Round-3 verdict ask #4: q8 runs 16x below q7 on CPU with no in-repo
attribution.  This times each pipeline stage as its own jitted program
over identical inputs:

  1. source generation + tumble windowing (both sides)
  2. join apply_begin (state update + emission staging)
  3. emission window 0 materialization (emit_window)
  4. the full per-chunk step (everything incl. extra windows + MV)

Usage:
  JAX_PLATFORMS=cpu python scripts/profile_q8.py            # timings
  JAX_PLATFORMS=cpu python scripts/profile_q8.py --assert   # regression
  ... --assert --small    # reduced state sizes (the CI/pytest wrapper)
  ... --assert --sharded  # 8 host-emulated devices: the SHARDED gate
                          # (1 fused dispatch per window, 0 per-chunk
                          # host dispatches, exchange-bytes budget,
                          # per-shard delta snapshots, probe audit)

``--assert`` turns the structural q8 invariants into hard failures so
probe-count and dispatch-count regressions fail loudly instead of
silently re-widening the join gap (exit 1 + named violation):

  - exactly ONE lookup_or_insert per append-only join side per chunk
    (trace-time probe audit of the fused (hash, rank) pool update);
  - the whole inter-barrier window dispatches as ONE fused program
    (DagJob.run_chunks) — zero per-chunk host dispatches;
  - steady-state probe effort stays bounded (device probe_iters per
    chunk within budget — load-factor / tombstone regressions show up
    here);
  - steady-state emission drains in ONE window per chunk (out_capacity
    sizing regressions show up as extra drain-loop trips);
  - join state error counters (overflow/inconsistency/emit_overflow)
    all zero.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import risingwave_tpu  # noqa: F401,E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from risingwave_tpu.sql import Engine  # noqa: E402
from risingwave_tpu.sql.planner import PlannerConfig  # noqa: E402
from risingwave_tpu.stream.runtime import _snapshot_copy  # noqa: E402

CAP = 8192

#: steady-state per-chunk budget on fused-probe loop trips: the ranked
#: probe resolves in ~4 rounds at bench load factors; tombstone pileup
#: or an overfull table shows up as a climb well past this
PROBE_ITERS_BUDGET = 24
#: steady-state emission windows per probe chunk (q8 emits a few
#: hundred matches per 8k chunk — one out_capacity window covers it)
DRAIN_WINDOWS_BUDGET = 1.25


def timeit(name, fn, n=20):
    jax.block_until_ready(fn())  # compile
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{name:42s} {dt * 1e3:9.2f} ms  "
          f"({CAP / dt / 1e6:7.2f}M rows/s/side)", flush=True)
    return dt


#: per-traced-exchange payload budget, BYTES PER ROW-SLOT: the
#: all_to_all moves n_shards*cap bucket slots of the q8 prep schema
#: (~170 B/row with the string column); a schema/bucketing regression
#: (extra columns, per-window exchanges) blows through this
EXCHANGE_BYTES_PER_SLOT_BUDGET = 512
#: traced exchange sites across ALL compiled sharded q8 programs (the
#: fused window traces 2 — one per join side, fori_loop traces its
#: body once; barrier/backfill/spill programs add a handful).  A
#: per-round or per-window exchange regression multiplies this.
EXCHANGE_CALLS_BUDGET = 24
#: steady-state per-shard dirty fraction bound: q8's tag-table scatter
#: dirties 10-40% of blocks per window at bench rate; 1.0 = the
#: full-copy path came back
DIRTY_RATIO_BOUND = 0.9


def build_engine(small: bool, cap: int) -> Engine:
    if small:
        cfg = PlannerConfig(
            chunk_capacity=cap,
            agg_table_size=1 << 12, agg_emit_capacity=1024,
            join_left_table_size=1 << 14, join_right_table_size=1 << 14,
            join_pool_size=1 << 18, join_out_capacity=1 << 10,
            mv_table_size=1 << 12, mv_ring_size=1 << 16,
        )
    else:
        cfg = PlannerConfig(
            chunk_capacity=cap,
            agg_table_size=1 << 18, agg_emit_capacity=4096,
            join_left_table_size=1 << 22, join_right_table_size=1 << 18,
            join_pool_size=1 << 22, join_out_capacity=1 << 15,
            mv_table_size=1 << 18, mv_ring_size=1 << 23,
        )
    eng = Engine(cfg)
    eng.execute("""
    CREATE SOURCE person (
        id BIGINT, name VARCHAR, date_time TIMESTAMP,
        WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
    ) WITH (connector = 'nexmark', nexmark.table = 'person',
            nexmark.event.rate = '1000000');
    CREATE SOURCE auction (
        id BIGINT, seller BIGINT, reserve BIGINT, expires TIMESTAMP,
        date_time TIMESTAMP,
        WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
    ) WITH (connector = 'nexmark', nexmark.table = 'auction',
            nexmark.event.rate = '1000000');
    CREATE MATERIALIZED VIEW bench_mv AS
    SELECT p.id AS id, p.name AS name, a.reserve AS reserve
    FROM TUMBLE(person, date_time, INTERVAL '1' SECOND) p
    JOIN TUMBLE(auction, date_time, INTERVAL '1' SECOND) a
    ON p.id = a.seller AND p.window_start = a.window_start;
    """)
    return eng


def run_assert(small: bool) -> int:
    """The regression-assertion mode (per-stage budget check)."""
    cap = 1024 if small else CAP
    eng = build_engine(small, cap)
    failures: list[str] = []

    # dispatch count: the inter-barrier window must be ONE fused
    # dispatch — count per-chunk host dispatches under the fused path
    from risingwave_tpu.stream.dag import DagJob
    per_chunk_calls = {"n": 0}
    orig_run_chunk = DagJob.run_chunk

    def counting_run_chunk(self, src):
        per_chunk_calls["n"] += 1
        return orig_run_chunk(self, src)

    DagJob.run_chunk = counting_run_chunk
    try:
        eng.tick(barriers=2, chunks_per_barrier=8)
    finally:
        DagJob.run_chunk = orig_run_chunk
    if per_chunk_calls["n"] != 0:
        failures.append(
            f"dispatch-count: {per_chunk_calls['n']} per-chunk host "
            "dispatches — the inter-barrier window no longer runs as "
            "one fused DagJob.run_chunks program"
        )

    # probe count: exactly one lookup_or_insert per pool side per chunk
    audit = eng.audit_join_probe_counts()
    if not audit:
        failures.append("probe-count: no pool join sides found to audit")
    for (jname, node, jside), stats in audit.items():
        if stats["lookup_or_insert"] != 1 or stats["lookup"] != 0:
            failures.append(
                f"probe-count: {jname} node {node} {jside} update "
                f"compiles {stats['lookup_or_insert']} lookup_or_insert"
                f" + {stats['lookup']} lookup calls (want exactly 1+0)"
            )

    # device-counter budgets (one readback, post-run)
    eng.collect_join_metrics()
    m = eng.metrics
    job = eng.jobs[0]
    from risingwave_tpu.stream.dag import JoinNode
    jidx = next(i for i, n in enumerate(job.nodes)
                if isinstance(n, JoinNode))
    labels = dict(job=job.name, node=str(jidx))
    iters = m.get("join_probe_iters_per_chunk", **labels)
    if iters > PROBE_ITERS_BUDGET:
        failures.append(
            f"probe-effort: {iters:.1f} fused-probe loop trips per "
            f"chunk (budget {PROBE_ITERS_BUDGET}) — table load factor "
            "or tombstone pileup regressed"
        )
    windows = m.get("join_drain_windows_per_chunk", **labels)
    if windows > DRAIN_WINDOWS_BUDGET:
        failures.append(
            f"drain-loop: {windows:.2f} emission windows per chunk "
            f"(budget {DRAIN_WINDOWS_BUDGET}) — out_capacity sizing "
            "or emission staging regressed"
        )

    # observability gate (trace-lite): the engine must attribute
    # barrier time per phase on its scrape surface for the bench job
    for phase in ("dispatch", "seal"):
        try:
            m.quantile("barrier_phase_seconds", 0.5,
                       job=job.name, phase=phase)
        except KeyError:
            failures.append(
                "observability: no barrier_phase_seconds"
                f"{{job={job.name},phase={phase}}} histogram — "
                "barrier-phase attribution regressed"
            )

    # error counters must be clean (the audit barrier would raise, but
    # assert explicitly so this mode stands alone)
    import numpy as np
    st = job.states[jidx]
    for sname in ("left", "right"):
        s = getattr(st, sname)
        for attr in ("overflow", "inconsistency"):
            v = int(np.asarray(getattr(s, attr)))
            if v:
                failures.append(f"counters: {sname}.{attr} = {v}")
    if int(np.asarray(st.emit_overflow)):
        failures.append(
            f"counters: emit_overflow = {int(np.asarray(st.emit_overflow))}"
        )

    if failures:
        print("profile_q8 --assert: FAIL", flush=True)
        for f in failures:
            print(f"  - {f}", flush=True)
        return 1
    print(
        "profile_q8 --assert: OK — 1 probe/side/chunk, fused dispatch, "
        f"probe iters/chunk {iters:.1f} <= {PROBE_ITERS_BUDGET}, "
        f"windows/chunk {windows:.2f} <= {DRAIN_WINDOWS_BUDGET}",
        flush=True,
    )
    return 0


def run_assert_sharded() -> int:
    """The SHARDED regression gate (ISSUE 9): q8 over an 8-device mesh
    must run each barrier-to-barrier window as ONE fused shard_map
    dispatch — zero per-chunk host dispatches — with bounded exchange
    traffic and per-shard DELTA snapshots (dirty-fraction cost, not
    full-copy).  Structural invariants only: this 1-core box cannot
    show wall-clock scaling on host-emulated devices."""
    import tempfile

    import numpy as np

    import jax

    if len(jax.devices()) < 8:
        print(f"profile_q8 --sharded: {len(jax.devices())} devices "
              "visible (need 8 host-emulated); re-exec with "
              "--xla_force_host_platform_device_count", flush=True)
        if os.environ.get("RWT_SHARDED_REEXEC"):
            return 1
        env = dict(os.environ)
        env["RWT_SHARDED_REEXEC"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
        os.execve(sys.executable, [sys.executable] + sys.argv, env)

    from risingwave_tpu.parallel.exchange import (
        EXCHANGE_TRACE,
        reset_exchange_trace,
    )
    from risingwave_tpu.stream.dag import DagJob, JoinNode

    cap = 1024
    rounds = 8
    failures: list[str] = []
    data_dir = tempfile.mkdtemp(prefix="rwt_profile_q8_sharded_")

    # the --small shapes plus a durable store (the delta-snapshot gate
    # needs the digest-mode shadow) and mesh parallelism
    eng = Engine(PlannerConfig(
        chunk_capacity=cap,
        agg_table_size=1 << 12, agg_emit_capacity=1024,
        join_left_table_size=1 << 14, join_right_table_size=1 << 14,
        join_pool_size=1 << 18, join_out_capacity=1 << 10,
        mv_table_size=1 << 12, mv_ring_size=1 << 18,
    ), data_dir=data_dir)
    eng.execute("SET streaming_parallelism = 8")
    eng.execute("""
    CREATE SOURCE person (
        id BIGINT, name VARCHAR, date_time TIMESTAMP,
        WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
    ) WITH (connector = 'nexmark', nexmark.table = 'person',
            nexmark.event.rate = '1000000');
    CREATE SOURCE auction (
        id BIGINT, seller BIGINT, reserve BIGINT, expires TIMESTAMP,
        date_time TIMESTAMP,
        WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
    ) WITH (connector = 'nexmark', nexmark.table = 'auction',
            nexmark.event.rate = '1000000');
    """)
    reset_exchange_trace()
    eng.execute("""
    CREATE MATERIALIZED VIEW bench_mv AS
    SELECT p.id AS id, p.name AS name, a.reserve AS reserve
    FROM TUMBLE(person, date_time, INTERVAL '1' SECOND) p
    JOIN TUMBLE(auction, date_time, INTERVAL '1' SECOND) a
    ON p.id = a.seller AND p.window_start = a.window_start;
    """)
    job = eng.jobs[0]
    if not (isinstance(job, DagJob) and job.mesh is not None
            and job.n_shards == 8):
        failures.append(
            f"plan: q8 did not shard over the mesh (mesh="
            f"{getattr(job, 'mesh', None)}, type {type(job).__name__})"
        )
        _report(failures)
        return 1

    # dispatch count: the whole inter-barrier window must be ONE fused
    # shard_map program — zero per-chunk host dispatches
    per_chunk_calls = {"n": 0}
    orig_run_chunk = DagJob.run_chunk

    def counting_run_chunk(self, src):
        per_chunk_calls["n"] += 1
        return orig_run_chunk(self, src)

    DagJob.run_chunk = counting_run_chunk
    try:
        eng.tick(barriers=3, chunks_per_barrier=rounds)
    finally:
        DagJob.run_chunk = orig_run_chunk
    if per_chunk_calls["n"] != 0:
        failures.append(
            f"dispatch-count: {per_chunk_calls['n']} per-chunk host "
            "dispatches — the sharded window no longer runs as one "
            "fused shard_map program"
        )
    if rounds not in job._fused_multi:
        failures.append(
            f"dispatch-count: no fused {rounds}-round program cached "
            f"(have {sorted(job._fused_multi)})"
        )
    if job.fused_fallbacks:
        failures.append(
            f"dispatch-count: fused fallbacks {job.fused_fallbacks}"
        )

    # exchange budget: traced sites + per-slot payload bytes
    calls = EXCHANGE_TRACE["calls"]
    if calls == 0:
        failures.append("exchange: no all_to_all traced in the "
                        "sharded programs")
    elif calls > EXCHANGE_CALLS_BUDGET:
        failures.append(
            f"exchange: {calls} traced exchange sites (budget "
            f"{EXCHANGE_CALLS_BUDGET}) — a per-round/per-window "
            "exchange crept in"
        )
    if calls:
        slots = calls * job.n_shards * cap
        per_slot = EXCHANGE_TRACE["bytes"] / slots
        if per_slot > EXCHANGE_BYTES_PER_SLOT_BUDGET:
            failures.append(
                f"exchange: {per_slot:.0f} B per bucket slot (budget "
                f"{EXCHANGE_BYTES_PER_SLOT_BUDGET}) — exchange payload "
                "schema regressed"
            )

    # per-shard shadow snapshots: delta kind + bounded dirty fraction
    kinds = [eng.checkpoint_store.checkpoint_kind(job.name, e)
             for e in eng.checkpoint_store.epochs(job.name)]
    if "delta" not in kinds:
        failures.append(
            f"snapshot: no delta checkpoint in the window (kinds "
            f"{kinds}) — the per-shard shadow is not feeding the "
            "delta store"
        )
    shadow = job._shadow
    if shadow is None:
        failures.append("snapshot: no shadow snapshot on the mesh job")
    else:
        if shadow.shard_rows != 8:
            failures.append(
                f"snapshot: shadow digests flat (shard_rows="
                f"{shadow.shard_rows}) — per-shard lanes lost"
            )
        ratio = shadow.dirty_ratio()
        if not (0.0 < ratio <= DIRTY_RATIO_BOUND):
            failures.append(
                f"snapshot: dirty-block ratio {ratio:.3f} outside "
                f"(0, {DIRTY_RATIO_BOUND}] — full-copy behaviour "
                "(or a dead digest diff)"
            )

    # probe count: the per-shard update body still compiles exactly
    # ONE lookup_or_insert per append-only pool side
    audit = eng.audit_join_probe_counts()
    if not audit:
        failures.append("probe-count: no pool join sides found")
    for (jname, node, jside), stats in audit.items():
        if stats["lookup_or_insert"] != 1 or stats["lookup"] != 0:
            failures.append(
                f"probe-count: {jname} node {node} {jside} compiles "
                f"{stats['lookup_or_insert']}+{stats['lookup']} probe "
                "calls (want exactly 1+0)"
            )

    # error counters clean, summed over the shard axis
    jidx = next(i for i, n in enumerate(job.nodes)
                if isinstance(n, JoinNode))
    st = job.states[jidx]
    for sname in ("left", "right"):
        s = getattr(st, sname)
        for attr in ("overflow", "inconsistency"):
            v = int(np.asarray(getattr(s, attr)).sum())
            if v:
                failures.append(f"counters: {sname}.{attr} = {v}")
    if int(np.asarray(st.emit_overflow).sum()):
        failures.append(
            f"counters: emit_overflow = "
            f"{int(np.asarray(st.emit_overflow).sum())}"
        )

    if failures:
        _report(failures)
        return 1
    print(
        "profile_q8 --assert --sharded: OK — 1 fused dispatch per "
        f"{rounds}-round window on 8 shards, 0 per-chunk host "
        f"dispatches, {calls} traced exchange sites, dirty ratio "
        f"{shadow.dirty_ratio():.3f} <= {DIRTY_RATIO_BOUND}, delta "
        "snapshots, 1 probe/side/chunk",
        flush=True,
    )
    return 0


def _report(failures: list) -> None:
    print("profile_q8 --assert --sharded: FAIL", flush=True)
    for f in failures:
        print(f"  - {f}", flush=True)


def main():
    if "--assert" in sys.argv:
        if "--sharded" in sys.argv:
            sys.exit(run_assert_sharded())
        sys.exit(run_assert(small="--small" in sys.argv))
    eng = build_engine(False, CAP)
    eng.tick(barriers=2, chunks_per_barrier=2)  # warm state + compile
    job = eng.jobs[0]
    from risingwave_tpu.stream.dag import JoinNode

    jidx = next(i for i, n in enumerate(job.nodes)
                if isinstance(n, JoinNode))
    join = job.nodes[jidx].join
    # prep fragments feeding the join (wm filter + tumble per side)
    src = "p"
    reader = job.sources[src]

    prep_idx = next(
        i for i, n in enumerate(job.nodes)
        if not isinstance(n, JoinNode) and n.input == ("source", src)
    )
    prep = job.nodes[prep_idx].fragment

    @jax.jit
    def gen_only(k0):
        return reader.impl(k0, reader.cap)

    @jax.jit
    def gen_prep(states, k0):
        chunk = reader.impl(k0, reader.cap)
        return prep._step_impl(states, chunk)

    # donated, as the real step program runs it: the state updates in
    # place; an un-donated trace would copy the multi-hundred-MB side
    # state every call and time the memcpy, not the join
    join_begin = jax.jit(
        lambda jstate, chunk: join.apply_begin(jstate, chunk, "left"),
        donate_argnums=(0,),
    )

    @jax.jit
    def emit0(jstate, pending):
        build = join.build_rows_of(jstate, "left")
        return join.emit_window(build, pending, jnp.int32(0), "left")

    k0 = jnp.int64(10_000_000)
    timeit("source gen only", lambda: gen_only(k0))
    st_prep = job.states[prep_idx]
    _, chunk = gen_prep(st_prep, k0)
    timeit("gen + wm + tumble", lambda: gen_prep(st_prep, k0)[1])
    jstate = _snapshot_copy(job.states[jidx])
    st2, pending = join_begin(jstate, chunk)

    def begin_threaded():
        # thread the donated state: measures the steady-state in-place
        # update cost
        nonlocal_state = begin_threaded.state
        st, pend = join_begin(nonlocal_state, chunk)
        begin_threaded.state = st
        return pend

    begin_threaded.state = st2
    timeit("join apply_begin (donated)", begin_threaded)
    st3 = begin_threaded.state
    _, pending = jax.jit(
        lambda jstate, chunk: join.apply_begin(jstate, chunk, "left")
    )(st3, chunk)
    timeit("emit window 0", lambda: emit0(st3, pending)[0])
    print("max_windows:", join.max_windows(CAP),
          "out_capacity:", join.out_capacity)
    print("pending total (this chunk):", int(pending.total))

    # whole-step reference (the real per-chunk cost)
    prog, fused = job._step_programs.get(src, (None, None))
    if prog is None:
        job._step_programs[src] = job._make_step(src)
        prog, fused = job._step_programs[src]
    job.states = prog(job.states, k0)
    jax.block_until_ready(job.states)

    def full():
        return prog(job.states, jnp.int64(reader.next_base()))

    t0 = time.perf_counter()
    N = 20
    for _ in range(N):
        job.states = full()
    jax.block_until_ready(job.states)
    dt = (time.perf_counter() - t0) / N
    print(f"{'FULL step (person side)':42s} {dt * 1e3:9.2f} ms  "
          f"({CAP / dt / 1e6:7.2f}M rows/s/side)")


if __name__ == "__main__":
    main()
