"""Attribute q8's cost: stage-by-stage timings of the windowed join.

Round-3 verdict ask #4: q8 runs 16x below q7 on CPU with no in-repo
attribution.  This times each pipeline stage as its own jitted program
over identical inputs:

  1. source generation + tumble windowing (both sides)
  2. join apply_begin (state update + emission staging)
  3. emission window 0 materialization (emit_window)
  4. the full per-chunk step (everything incl. extra windows + MV)

Usage: JAX_PLATFORMS=cpu python scripts/profile_q8.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import risingwave_tpu  # noqa: F401,E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from risingwave_tpu.sql import Engine  # noqa: E402
from risingwave_tpu.sql.planner import PlannerConfig  # noqa: E402

CAP = 8192


def timeit(name, fn, n=20):
    jax.block_until_ready(fn())  # compile
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{name:42s} {dt * 1e3:9.2f} ms  "
          f"({CAP / dt / 1e6:7.2f}M rows/s/side)", flush=True)
    return dt


def main():
    eng = Engine(PlannerConfig(
        chunk_capacity=CAP,
        agg_table_size=1 << 18, agg_emit_capacity=4096,
        join_left_table_size=1 << 22, join_right_table_size=1 << 18,
        join_pool_size=1 << 22, join_out_capacity=1 << 15,
        mv_table_size=1 << 18, mv_ring_size=1 << 23,
    ))
    eng.execute("""
    CREATE SOURCE person (
        id BIGINT, name VARCHAR, date_time TIMESTAMP,
        WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
    ) WITH (connector = 'nexmark', nexmark.table = 'person',
            nexmark.event.rate = '1000000');
    CREATE SOURCE auction (
        id BIGINT, seller BIGINT, reserve BIGINT, expires TIMESTAMP,
        date_time TIMESTAMP,
        WATERMARK FOR date_time AS date_time - INTERVAL '4' SECOND
    ) WITH (connector = 'nexmark', nexmark.table = 'auction',
            nexmark.event.rate = '1000000');
    CREATE MATERIALIZED VIEW bench_mv AS
    SELECT p.id AS id, p.name AS name, a.reserve AS reserve
    FROM TUMBLE(person, date_time, INTERVAL '1' SECOND) p
    JOIN TUMBLE(auction, date_time, INTERVAL '1' SECOND) a
    ON p.id = a.seller AND p.window_start = a.window_start;
    """)
    eng.tick(barriers=2, chunks_per_barrier=2)  # warm state + compile
    job = eng.jobs[0]
    from risingwave_tpu.stream.dag import JoinNode

    jidx = next(i for i, n in enumerate(job.nodes)
                if isinstance(n, JoinNode))
    join = job.nodes[jidx].join
    # prep fragments feeding the join (wm filter + tumble per side)
    src = "p"
    reader = job.sources[src]

    prep_idx = next(
        i for i, n in enumerate(job.nodes)
        if not isinstance(n, JoinNode) and n.input == ("source", src)
    )
    prep = job.nodes[prep_idx].fragment

    @jax.jit
    def gen_only(k0):
        return reader.impl(k0, reader.cap)

    @jax.jit
    def gen_prep(states, k0):
        chunk = reader.impl(k0, reader.cap)
        return prep._step_impl(states, chunk)

    @jax.jit
    def join_begin(jstate, chunk):
        return join.apply_begin(jstate, chunk, "left")

    @jax.jit
    def emit0(jstate, pending):
        build = join.build_rows_of(jstate, "left")
        return join.emit_window(build, pending, jnp.int32(0), "left")

    k0 = jnp.int64(10_000_000)
    timeit("source gen only", lambda: gen_only(k0))
    st_prep = job.states[prep_idx]
    _, chunk = gen_prep(st_prep, k0)
    timeit("gen + wm + tumble", lambda: gen_prep(st_prep, k0)[1])
    jstate = job.states[jidx]
    st2, pending = join_begin(jstate, chunk)
    timeit("join apply_begin", lambda: join_begin(jstate, chunk)[1])
    timeit("emit window 0", lambda: emit0(st2, pending)[0])
    print("max_windows:", join.max_windows(CAP),
          "out_capacity:", join.out_capacity)
    print("pending total (this chunk):", int(pending.total))

    # whole-step reference (the real per-chunk cost)
    prog, fused = job._step_programs.get(src, (None, None))
    if prog is None:
        job._step_programs[src] = job._make_step(src)
        prog, fused = job._step_programs[src]
    job.states = prog(job.states, k0)
    jax.block_until_ready(job.states)

    def full():
        return prog(job.states, jnp.int64(reader.next_base()))

    t0 = time.perf_counter()
    N = 20
    for _ in range(N):
        job.states = full()
    jax.block_until_ready(job.states)
    dt = (time.perf_counter() - t0) / N
    print(f"{'FULL step (person side)':42s} {dt * 1e3:9.2f} ms  "
          f"({CAP / dt / 1e6:7.2f}M rows/s/side)")


if __name__ == "__main__":
    main()
