"""Cluster stress: sustained ingest + worker SIGKILL + pinned reads.

The acceptance harness for the cluster-lite control plane (ISSUE 3):
a 1-meta + N-compute cluster (workers are REAL processes) maintaining
two nexmark MVs under continuous global barrier rounds while

- one worker is SIGKILLed mid-stream (its jobs are reassigned to
  survivors and replayed from the last committed cluster epoch),
- concurrent serving reads — routed through the meta's pinned epoch —
  run across the failover and must observe only committed state with
  ZERO errors,
- after the target number of committed rounds, every MV's contents
  must be byte-identical to an undisturbed single-node run of the
  same config and round count.

Run standalone (prints one JSON summary line)::

    python scripts/cluster_stress.py --rounds 24 --assert

or the short ``slow``-marked pytest wrapper
(tests/test_cluster_stress.py).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, ".")  # repo root

CONFIG = {
    "streaming": {"chunk_size": 256},
    "state": {"agg_table_size": 1 << 10, "agg_emit_capacity": 256,
              "mv_table_size": 1 << 10, "mv_ring_size": 1 << 12},
    "storage": {"checkpoint_keep_epochs": 4},
}

DDL = [
    """CREATE SOURCE bid (
        auction BIGINT, bidder BIGINT, price BIGINT,
        channel VARCHAR, url VARCHAR, date_time TIMESTAMP
    ) WITH (connector = 'nexmark', nexmark.table = 'bid')""",
    """CREATE MATERIALIZED VIEW q7 AS
    SELECT window_start, max(price) AS max_price, count(*) AS bids
    FROM TUMBLE(bid, date_time, INTERVAL '1' SECOND)
    GROUP BY window_start""",
    """CREATE MATERIALIZED VIEW qcnt AS
    SELECT auction % 16 AS a, count(*) AS n, sum(price) AS vol
    FROM bid GROUP BY auction % 16""",
]

READS = [
    "SELECT window_start, max_price, bids FROM q7",
    "SELECT a, n, vol FROM qcnt",
]


def _spawn_worker(meta_port: int, data_dir: str, idx: int):
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"))
    return subprocess.Popen(
        [sys.executable, "-m", "risingwave_tpu.server",
         "--role", "compute", "--meta", f"127.0.0.1:{meta_port}",
         "--data-dir", data_dir, "--config-json", json.dumps(CONFIG),
         "--heartbeat-interval", "0.25"],
        stdout=subprocess.DEVNULL,
        stderr=open(os.path.join(data_dir, f"worker{idx}.log"), "wb"),
        env=env,
    )


def run(rounds: int = 24, workers: int = 2, kill_at_round: int = 8,
        chunks_per_barrier: int = 1, readers: int = 2,
        data_dir: str | None = None) -> dict:
    from risingwave_tpu.cluster import MetaService
    from risingwave_tpu.common.config import RwConfig
    from risingwave_tpu.sql.engine import Engine

    data_dir = data_dir or tempfile.mkdtemp(prefix="cluster_stress_")
    meta = MetaService(data_dir, heartbeat_timeout_s=4.0)
    meta.start(port=0)
    procs = [_spawn_worker(meta.rpc_port, data_dir, i)
             for i in range(workers)]
    state = {"reads": 0, "read_errors": [], "rounds_committed": 0,
             "retries": 0}
    stop = threading.Event()

    def read_loop():
        while not stop.is_set():
            for sql in READS:
                try:
                    meta.serve(sql)
                    state["reads"] += 1
                except Exception as e:  # noqa: BLE001
                    state["read_errors"].append(repr(e))
            time.sleep(0.02)

    try:
        deadline = time.monotonic() + 120
        while len(meta.live_workers()) < workers:
            if time.monotonic() > deadline:
                raise TimeoutError("workers never registered")
            for p in procs:
                if p.poll() is not None:
                    raise RuntimeError(
                        f"worker died at startup (logs in {data_dir})")
            time.sleep(0.25)

        for sql in DDL:
            meta.execute_ddl(sql)

        threads = [threading.Thread(target=read_loop, daemon=True)
                   for _ in range(readers)]
        for t in threads:
            t.start()

        killed_pid = None
        barrier_baseline: list = []
        t_start = time.monotonic()
        for r in range(1, rounds + 1):
            round_deadline = time.monotonic() + 240
            while True:
                res = meta.tick(chunks_per_barrier)
                if res["committed"]:
                    break
                state["retries"] += 1
                if time.monotonic() > round_deadline:
                    raise TimeoutError(f"round {r} never committed")
                time.sleep(0.2)
            state["rounds_committed"] = r
            if r == 2:
                # tail gate baseline: rounds 1-2 pay jit compiles and
                # are excluded from the barrier-commit p99 ceiling
                barrier_baseline = meta.metrics.hist_counts(
                    "cluster_barrier_commit_seconds")
            if r == kill_at_round and killed_pid is None:
                st = meta.state()
                victim = next(w for w in st["workers"] if w["alive"]
                              and w["jobs"])
                killed_pid = victim["pid"]
                os.kill(killed_pid, signal.SIGKILL)
        wall = time.monotonic() - t_start

        stop.set()
        for t in threads:
            t.join(timeout=10)

        cluster_rows = [sorted(tuple(v) for v in meta.serve(sql)[1])
                        for sql in READS]

        # undisturbed single-node reference (same config + rounds)
        eng = Engine(RwConfig.from_dict(CONFIG))
        for sql in DDL:
            eng.execute(sql)
        eng.tick(barriers=rounds, chunks_per_barrier=chunks_per_barrier)
        single_rows = [
            sorted(tuple(int(x) for x in r) for r in eng.execute(sql))
            for sql in READS
        ]
        mismatches = sum(c != s
                         for c, s in zip(cluster_rows, single_rows))

        # unified metrics plane: ONE aggregated scrape must carry the
        # barrier-phase histograms and the spike-ratio gauge for every
        # live MV job (derived worker-side, merged meta-side)
        import re
        mtext = meta.cluster_metrics()
        phase_jobs = sorted(set(re.findall(
            r'barrier_phase_seconds_bucket\{[^}]*job="([^"]+)"',
            mtext)))
        spike_jobs = sorted(set(re.findall(
            r'barrier_spike_ratio\{[^}]*job="([^"]+)"', mtext)))

        # write-path tail gate inputs: barrier-commit p99 over the
        # post-warmup rounds (the round-15 metrics plane measured it;
        # this is the first ceiling asserted on it)
        barrier_commits = sum(meta.metrics.hist_counts(
            "cluster_barrier_commit_seconds"))
        barrier_p99 = meta.metrics.quantile_delta(
            "cluster_barrier_commit_seconds", 0.99, barrier_baseline)

        return {
            "rounds": rounds,
            "rounds_committed": state["rounds_committed"],
            "barrier_commits": barrier_commits,
            "barrier_commit_p99_s": barrier_p99,
            "workers": workers,
            "killed_pid": killed_pid,
            "failovers": meta.failovers,
            "cluster_epoch": meta.cluster_epoch,
            "manifest_epoch": meta.versions.max_committed_epoch,
            "reads": state["reads"],
            "read_errors": len(state["read_errors"]),
            "read_error_samples": state["read_errors"][:3],
            "tick_retries": state["retries"],
            "mv_mismatches": mismatches,
            "mv_rows": [len(r) for r in cluster_rows],
            "metrics_phase_jobs": phase_jobs,
            "metrics_spike_jobs": spike_jobs,
            "wall_seconds": round(wall, 2),
            "data_dir": data_dir,
        }
    finally:
        stop.set()
        for p in procs:
            if p.poll() is None:
                p.kill()
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        meta.stop()


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--rounds", type=int, default=24)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--kill-at-round", type=int, default=8)
    p.add_argument("--chunks-per-barrier", type=int, default=1)
    p.add_argument("--readers", type=int, default=2)
    p.add_argument("--max-barrier-p99", type=float, default=120.0,
                   help="ceiling (seconds) on post-warmup "
                        "barrier-commit p99 — generous for the "
                        "1-core CI box; the TPU-host target is far "
                        "tighter")
    p.add_argument("--assert", dest="check", action="store_true",
                   help="exit nonzero unless converged with 0 read "
                        "errors and exactly one failover")
    args = p.parse_args()
    summary = run(rounds=args.rounds, workers=args.workers,
                  kill_at_round=args.kill_at_round,
                  chunks_per_barrier=args.chunks_per_barrier,
                  readers=args.readers)
    print(json.dumps(summary))
    if args.check:
        mv_jobs = {"q7", "qcnt"}
        ok = (summary["read_errors"] == 0
              and summary["mv_mismatches"] == 0
              and summary["failovers"] == 1
              and summary["rounds_committed"] == summary["rounds"]
              # observability gate: the aggregated scrape attributes
              # barrier time per phase and tracks the spike ratio for
              # every MV job that survived the run
              and mv_jobs <= set(summary["metrics_phase_jobs"])
              and mv_jobs <= set(summary["metrics_spike_jobs"])
              # write-path tail gate: every round observed a commit
              # latency, and the post-warmup p99 stays bounded
              and summary["barrier_commits"] >= summary["rounds"]
              and 0.0 < summary["barrier_commit_p99_s"]
              <= args.max_barrier_p99)
        raise SystemExit(0 if ok else 1)


if __name__ == "__main__":
    main()
