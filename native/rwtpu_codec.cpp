// Native codec for the storage layer: memcomparable key encoding,
// varint block encode/decode, crc32c checksums.
//
// Reference counterparts (design, not code): the memcomparable
// OrderedRowSerde (src/common/src/util/memcmp_encoding/) and the
// block-based SSTable format (src/storage/src/hummock/sstable/block.rs).
// The reference implements these in Rust; this is the C++ equivalent for
// the host-side storage path (the TPU compute path never touches it).
//
// Build: g++ -O3 -shared -fPIC rwtpu_codec.cpp -o librwtpu_codec.so

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------
// memcomparable scalar encodings: byte-wise lexicographic order == value
// order.  int64: flip sign bit, big-endian.  float64: flip sign bit for
// positives, all bits for negatives (IEEE754 total order), big-endian.

void mc_encode_i64(const int64_t* in, int64_t n, uint8_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        uint64_t u = (uint64_t)in[i] ^ 0x8000000000000000ULL;
        uint8_t* p = out + i * 8;
        for (int b = 0; b < 8; ++b) p[b] = (uint8_t)(u >> (56 - 8 * b));
    }
}

void mc_decode_i64(const uint8_t* in, int64_t n, int64_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t* p = in + i * 8;
        uint64_t u = 0;
        for (int b = 0; b < 8; ++b) u = (u << 8) | p[b];
        out[i] = (int64_t)(u ^ 0x8000000000000000ULL);
    }
}

void mc_encode_f64(const double* in, int64_t n, uint8_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        uint64_t u;
        memcpy(&u, &in[i], 8);
        if (u >> 63) u = ~u;              // negative: flip all
        else u |= 0x8000000000000000ULL;  // positive: flip sign
        uint8_t* p = out + i * 8;
        for (int b = 0; b < 8; ++b) p[b] = (uint8_t)(u >> (56 - 8 * b));
    }
}

void mc_decode_f64(const uint8_t* in, int64_t n, double* out) {
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t* p = in + i * 8;
        uint64_t u = 0;
        for (int b = 0; b < 8; ++b) u = (u << 8) | p[b];
        if (u >> 63) u &= 0x7FFFFFFFFFFFFFFFULL;
        else u = ~u;
        memcpy(&out[i], &u, 8);
    }
}

// ---------------------------------------------------------------------
// varint (LEB128) block of (key, value) records:
//   record := varint(klen) key varint(vlen) value
// Keys must be pre-sorted by the caller; the block is append-ordered.

static inline int put_varint(uint8_t* p, uint64_t v) {
    int n = 0;
    while (v >= 0x80) { p[n++] = (uint8_t)(v | 0x80); v >>= 7; }
    p[n++] = (uint8_t)v;
    return n;
}

static inline int get_varint(const uint8_t* p, const uint8_t* end,
                             uint64_t* v) {
    uint64_t x = 0;
    int shift = 0, n = 0;
    while (p + n < end) {
        uint8_t b = p[n++];
        x |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) { *v = x; return n; }
        shift += 7;
        if (shift > 63) return -1;
    }
    return -1;
}

// Returns bytes written, or -1 if out_cap is too small.
int64_t block_encode(const uint8_t* keys, const int64_t* key_offsets,
                     const uint8_t* vals, const int64_t* val_offsets,
                     int64_t n, uint8_t* out, int64_t out_cap) {
    int64_t w = 0;
    for (int64_t i = 0; i < n; ++i) {
        int64_t klen = key_offsets[i + 1] - key_offsets[i];
        int64_t vlen = val_offsets[i + 1] - val_offsets[i];
        if (w + 20 + klen + vlen > out_cap) return -1;
        w += put_varint(out + w, (uint64_t)klen);
        memcpy(out + w, keys + key_offsets[i], (size_t)klen);
        w += klen;
        w += put_varint(out + w, (uint64_t)vlen);
        memcpy(out + w, vals + val_offsets[i], (size_t)vlen);
        w += vlen;
    }
    return w;
}

// First pass: count records and total key/value bytes.
int64_t block_scan(const uint8_t* in, int64_t len, int64_t* n_out,
                   int64_t* key_bytes, int64_t* val_bytes) {
    const uint8_t* p = in;
    const uint8_t* end = in + len;
    int64_t n = 0, kb = 0, vb = 0;
    while (p < end) {
        uint64_t klen, vlen;
        int adv = get_varint(p, end, &klen);
        if (adv < 0) return -1;
        p += adv;
        // length-vs-remaining check BEFORE advancing: a huge varint
        // must not wrap the pointer past the bounds test
        if (klen > (uint64_t)(end - p)) return -1;
        p += klen;
        adv = get_varint(p, end, &vlen);
        if (adv < 0) return -1;
        p += adv;
        if (vlen > (uint64_t)(end - p)) return -1;
        p += vlen;
        ++n; kb += (int64_t)klen; vb += (int64_t)vlen;
    }
    *n_out = n; *key_bytes = kb; *val_bytes = vb;
    return 0;
}

// Second pass: fill key/value byte pools + offset arrays (n+1 each).
int64_t block_decode(const uint8_t* in, int64_t len,
                     uint8_t* keys, int64_t* key_offsets,
                     uint8_t* vals, int64_t* val_offsets) {
    const uint8_t* p = in;
    const uint8_t* end = in + len;
    int64_t i = 0, ko = 0, vo = 0;
    key_offsets[0] = 0; val_offsets[0] = 0;
    while (p < end) {
        uint64_t klen, vlen;
        int adv = get_varint(p, end, &klen);
        if (adv < 0) return -1;
        p += adv;
        memcpy(keys + ko, p, (size_t)klen);
        p += klen; ko += (int64_t)klen;
        adv = get_varint(p, end, &vlen);
        if (adv < 0) return -1;
        p += adv;
        memcpy(vals + vo, p, (size_t)vlen);
        p += vlen; vo += (int64_t)vlen;
        ++i;
        key_offsets[i] = ko; val_offsets[i] = vo;
    }
    return i;
}

// ---------------------------------------------------------------------
// crc32c (Castagnoli), bit-reflected, table-driven — block checksums.

static uint32_t crc_table[256];
static bool crc_init_done = false;

static void crc_init() {
    for (uint32_t i = 0; i < 256; ++i) {
        uint32_t c = i;
        for (int k = 0; k < 8; ++k)
            c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
        crc_table[i] = c;
    }
    crc_init_done = true;
}

uint32_t rw_crc32c(const uint8_t* data, int64_t n) {
    if (!crc_init_done) crc_init();
    uint32_t c = 0xFFFFFFFFu;
    for (int64_t i = 0; i < n; ++i)
        c = crc_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
    return ~c;
}

}  // extern "C"
